"""Wire-v5 combiner rows (ISSUE 9): host pre-reduced per-partition fold
tables replace the last per-record columns.

The byte-identity bar has two layers:

- TABLE bytes: the combiner tables a packer emits (counter deltas,
  DDSketch buckets, extremes) must equal a straight numpy reference
  reduction over the same records — native and numpy packers alike
  (the hypothesis property test mirrors the PR-8 row-bytes parity suite).
- SCAN results: a v5 scan's full document must equal the v4 scan's across
  (wire, segfile) × workers × K × mesh, including corruption/quarantine
  parity and v4↔v5 cross-format resume.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    CorruptionConfig,
    DispatchConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.obs.registry import default_registry
from kafka_topic_analyzer_tpu.packing import (
    pack_batch,
    packed_nbytes,
    section_byte_split,
    unpack_numpy,
)
from kafka_topic_analyzer_tpu.records import RecordBatch

from fake_broker import CorruptionInjector, FakeBroker

pytestmark = pytest.mark.wirev5

TOPIC = "wirev5.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 29}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


N_PARTS = 4
N_REC = 300
RECORDS = {p: _mk_records(p, N_REC) for p in range(N_PARTS)}


def _cfg(wire_format: int, **kw) -> AnalyzerConfig:
    base = dict(
        num_partitions=N_PARTS,
        batch_size=128,
        count_alive_keys=True,
        alive_bitmap_bits=16,
        enable_hll=True,
        hll_p=8,
        enable_quantiles=True,
        quantiles_per_partition=True,
    )
    base.update(kw)
    return AnalyzerConfig(wire_format=wire_format, **base)


def _full_doc(result) -> dict:
    return {
        "metrics": result.metrics.to_dict(
            result.start_offsets, result.end_offsets
        ),
        "start": result.start_offsets,
        "end": result.end_offsets,
        "degraded": result.degraded_partitions,
        "corrupt": result.corrupt_partitions,
    }


def _wire_scan(wire_format, workers=1, superbatch=1, backend_cls=TpuBackend,
               mesh=None, **cfg_kw):
    cfg = _cfg(wire_format, **cfg_kw)
    if mesh is not None:
        cfg = dataclasses.replace(cfg, mesh_shape=mesh)
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        backend = backend_cls(
            cfg, init_now_s=10**10,
            dispatch=DispatchConfig(superbatch=superbatch),
        )
        result = run_scan(
            TOPIC, src, backend, cfg.batch_size, ingest_workers=workers
        )
        src.close()
    return result


@pytest.fixture(scope="module")
def wire_v4_baseline():
    """The v4 scan — the byte-exact referee for every v5 configuration."""
    return _full_doc(_wire_scan(4))


# ---------------------------------------------------------------------------
# scan-level identity: (wire) × workers × K × mesh


@pytest.mark.parametrize("workers,superbatch", [
    (1, 1), (4, 1), (1, 4), (4, 4),
])
def test_v5_wire_scan_identical(wire_v4_baseline, workers, superbatch):
    result = _wire_scan(5, workers=workers, superbatch=superbatch)
    assert _full_doc(result) == wire_v4_baseline
    assert result.wire is not None and result.wire.format == 5


@pytest.mark.parametrize("mesh,superbatch", [((2, 1), 1), ((2, 1), 4)])
def test_v5_sharded_scan_identical(wire_v4_baseline, mesh, superbatch):
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    for wf in (4, 5):
        result = _wire_scan(wf, mesh=mesh, superbatch=superbatch,
                            backend_cls=ShardedTpuBackend)
        assert _full_doc(result) == wire_v4_baseline, wf


def test_v5_flat_hll_pair_mode_scan_identical(wire_v4_baseline):
    """Per-partition HLL in PAIR mode (the one v5 section that cannot ride
    unchanged — idx32 carries the register row).  hll_p=14 at B=128 forces
    pair mode; referee is the v4 scan of the same config."""
    a = _full_doc(_wire_scan(4, distinct_keys_per_partition=True, hll_p=14))
    b = _full_doc(_wire_scan(5, distinct_keys_per_partition=True, hll_p=14))
    assert a == b
    assert a != wire_v4_baseline  # the per-partition rows actually differ


# ---------------------------------------------------------------------------
# segfile cold path


def test_v5_segfile_scan_identical(tmp_path):
    from kafka_topic_analyzer_tpu.io.segfile import (
        SegmentDumpWriter,
        SegmentFileSource,
    )

    spec = SyntheticSpec(
        num_partitions=3, messages_per_partition=700, keys_per_partition=40,
        seed=5, key_null_permille=60, tombstone_permille=90,
    )
    d = str(tmp_path / "segs")
    writer = SegmentDumpWriter(d, "seg.topic", records_per_chunk=256)
    src = SyntheticSource(spec)
    writer.set_base_offsets(src.watermarks()[0])
    for b in src.batches(180):
        writer.append(b)
    writer.close()

    def scan(wf, workers=1):
        cfg = AnalyzerConfig(
            num_partitions=3, batch_size=128, count_alive_keys=True,
            alive_bitmap_bits=14, enable_hll=True, hll_p=8,
            enable_quantiles=True, wire_format=wf,
        )
        s = SegmentFileSource(d, "seg.topic")
        r = run_scan("seg.topic", s, TpuBackend(cfg, init_now_s=10**10),
                     128, ingest_workers=workers)
        return _full_doc(r)

    base = scan(4)
    assert scan(5) == base
    assert scan(5, workers=2) == base
    assert scan(4, workers=2) == base


# ---------------------------------------------------------------------------
# corruption parity


def test_v5_corruption_quarantine_parity(tmp_path):
    """Deterministic poison under --on-corruption=quarantine: the v5 scan
    classifies, accounts, and quarantines EXACTLY like the v4 scan."""
    def poisoned():
        inj = (
            CorruptionInjector()
            .flip_byte(1, chunk=1, offset=-1)
            .flip_byte(2, chunk=3, offset=-3)
        )
        return FakeBroker(
            TOPIC, RECORDS, max_records_per_fetch=50, corruption=inj,
            honor_partition_max_bytes=True,
        )

    def run(wf, qdir):
        cfg = _cfg(wf)
        with poisoned() as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC,
                overrides=dict(FAST_RETRY, **{"check.crcs": "true"}),
                corruption=CorruptionConfig(
                    policy="quarantine", quarantine_dir=qdir
                ),
            )
            r = run_scan(TOPIC, src, TpuBackend(cfg, init_now_s=10**10), 128)
            spans = src.corruption_spans()
            src.close()
        return _full_doc(r), spans

    doc4, spans4 = run(4, str(tmp_path / "q4"))
    doc5, spans5 = run(5, str(tmp_path / "q5"))
    assert doc5 == doc4
    assert sorted(doc5["corrupt"]) == [1, 2]
    assert spans5 == spans4
    assert sorted(os.listdir(tmp_path / "q5")) == sorted(
        os.listdir(tmp_path / "q4")
    )


# ---------------------------------------------------------------------------
# cross-format resume


class _Interrupt(Exception):
    pass


class _InterruptingSource(SyntheticSource):
    def __init__(self, spec, limit):
        super().__init__(spec)
        self.limit = limit

    def batches(self, batch_size, partitions=None, start_at=None):
        it = super().batches(batch_size, partitions, start_at)
        for i, b in enumerate(it):
            if start_at is None and i >= self.limit:
                raise _Interrupt()
            yield b


RESUME_SPEC = SyntheticSpec(
    num_partitions=3, messages_per_partition=2_000, keys_per_partition=80,
    tombstone_permille=150, seed=31,
)


@pytest.mark.parametrize("wf_first,wf_second", [(4, 5), (5, 4)])
def test_cross_format_resume(tmp_path, wf_first, wf_second):
    """A snapshot taken mid-scan under one wire format resumes under the
    other, reproducing the uninterrupted scan exactly — the format is
    execution strategy, outside the checkpoint fingerprint."""
    cfg_first = AnalyzerConfig(
        num_partitions=3, batch_size=512, count_alive_keys=True,
        alive_bitmap_bits=18, enable_hll=True, hll_p=10,
        enable_quantiles=True, wire_format=wf_first,
    )
    cfg_second = dataclasses.replace(cfg_first, wire_format=wf_second)
    full = run_scan(
        "t", SyntheticSource(RESUME_SPEC),
        TpuBackend(cfg_second, init_now_s=10**10), 512,
    ).metrics.to_dict(None, None)

    with pytest.raises(_Interrupt):
        run_scan(
            "t", _InterruptingSource(RESUME_SPEC, limit=5),
            TpuBackend(cfg_first, init_now_s=10**10), 512,
            snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
        )
    resumed = run_scan(
        "t", SyntheticSource(RESUME_SPEC),
        TpuBackend(cfg_second, init_now_s=0), 512,
        snapshot_dir=str(tmp_path), resume=True,
    )
    assert resumed.metrics.to_dict(None, None) == full


# ---------------------------------------------------------------------------
# combiner tables vs reference reduction (hypothesis property test)


def _reference_tables(batch: RecordBatch, cfg: AnalyzerConfig):
    """Straight numpy reference reduction of a batch's combiner tables —
    written against the metric DEFINITIONS (counter channels, tombstone
    exclusion, the shared edge table), independently of pack_batch."""
    from kafka_topic_analyzer_tpu.ops.ddsketch import (
        ddsketch_bucket_numpy,
        ddsketch_num_buckets,
    )

    nv = batch.num_valid
    p = np.asarray(batch.partition[:nv])
    kn = ~np.asarray(batch.key_null[:nv])
    vn = ~np.asarray(batch.value_null[:nv])
    kb = np.where(kn, batch.key_len[:nv], 0).astype(np.int64)
    vb = np.where(vn, batch.value_len[:nv], 0).astype(np.int64)
    counts = np.zeros((cfg.num_partitions, 7), np.int64)
    for i in range(nv):
        row = counts[p[i]]
        row[0] += 1
        row[1] += 0 if vn[i] else 1
        row[2] += 1 if vn[i] else 0
        row[3] += 0 if kn[i] else 1
        row[4] += 1 if kn[i] else 0
        row[5] += kb[i]
        row[6] += vb[i]
    nb = ddsketch_num_buckets(cfg.quantile_buckets)
    q_rows = cfg.num_partitions if cfg.quantiles_per_partition else 1
    qt = np.zeros((q_rows, nb), np.int64)
    sizes = kb + vb
    for i in range(nv):
        if not vn[i]:
            continue  # tombstones excluded, like the size extremes
        idx = int(ddsketch_bucket_numpy(
            np.array([sizes[i]]), cfg.quantile_gamma, cfg.quantile_buckets
        )[0])
        qt[p[i] if q_rows > 1 else 0, idx] += 1
    return counts, qt


def _hyp_batch(draw):
    from hypothesis import strategies as st

    n = draw(st.integers(min_value=0, max_value=96))
    parts = draw(st.integers(min_value=1, max_value=5))
    # Histogram-edge sizes: include exact gamma-power boundaries so a
    # searchsorted off-by-one fails here, plus 0/1 and u16-max keys.
    key_len = np.array(
        [draw(st.sampled_from([0, 1, 7, 64, 65535])) for _ in range(n)],
        dtype=np.int32,
    )
    value_len = np.array(
        [draw(st.sampled_from([0, 1, 2, 100, 101, 4096, 1 << 20]))
         for _ in range(n)],
        dtype=np.int32,
    )
    key_null = np.array(
        [draw(st.booleans()) for _ in range(n)], dtype=bool
    )
    value_null = np.array(
        [draw(st.booleans()) for _ in range(n)], dtype=bool
    )
    batch = RecordBatch(
        partition=np.array(
            [draw(st.integers(0, parts - 1)) for _ in range(n)],
            dtype=np.int32,
        ),
        key_len=np.where(key_null, 0, key_len).astype(np.int32),
        value_len=np.where(value_null, 0, value_len).astype(np.int32),
        key_null=key_null,
        value_null=value_null,
        ts_s=np.array(
            [draw(st.integers(0, 2**31)) for _ in range(n)], dtype=np.int64
        ),
        key_hash32=np.array(
            [draw(st.integers(0, 2**32 - 1)) for _ in range(n)],
            dtype=np.uint32,
        ),
        key_hash64=np.array(
            [draw(st.integers(0, 2**63)) for _ in range(n)],
            dtype=np.uint64,
        ),
        valid=np.ones(n, dtype=bool),
    )
    return batch, parts


def test_combiner_tables_match_reference_reduction():
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    native = pytest.importorskip("kafka_topic_analyzer_tpu.io.native")
    use_native = native.native_available()

    @settings(max_examples=60, deadline=None)
    @given(st.data())
    def run(data):
        batch, parts = _hyp_batch(data.draw)
        cfg = AnalyzerConfig(
            num_partitions=parts, batch_size=96, enable_quantiles=True,
            quantiles_per_partition=data.draw(st.booleans()),
            wire_format=5,
        )
        ref_counts, ref_qt = _reference_tables(batch, cfg)
        for nat in ([False, True] if use_native else [False]):
            got = unpack_numpy(
                pack_batch(batch, cfg, use_native=nat).copy(), cfg
            )
            assert np.array_equal(np.asarray(got["counts"]), ref_counts), nat
            assert np.array_equal(np.asarray(got["qcounts"]), ref_qt), nat

    run()


# ---------------------------------------------------------------------------
# packer units


def _rand_batch(seed: int, n: int, parts: int) -> RecordBatch:
    rng = np.random.default_rng(seed)
    key_null = rng.random(n) < 0.1
    value_null = rng.random(n) < 0.15
    batch = RecordBatch(
        partition=np.sort(rng.integers(0, parts, n).astype(np.int32)),
        key_len=np.where(key_null, 0, rng.integers(0, 40, n)).astype(np.int32),
        value_len=np.where(value_null, 0, rng.integers(0, 500, n)).astype(np.int32),
        key_null=key_null,
        value_null=value_null,
        ts_s=rng.integers(0, 2**31, n),
        key_hash32=rng.integers(0, 2**32, n, dtype=np.uint32),
        key_hash64=rng.integers(0, 2**63, n, dtype=np.uint64),
        valid=np.ones(n, dtype=bool),
    )
    batch.key_hash32[key_null] = 0
    batch.key_hash64[key_null] = 0
    return batch


def test_v5_native_rows_equal_numpy_rows():
    """Native and numpy v5 packers agree byte-for-byte on every section
    except the alive pairs' documented ordering difference (compared as
    sets, counts exact)."""
    native = pytest.importorskip("kafka_topic_analyzer_tpu.io.native")
    if not native.native_available():
        pytest.skip("native shim unavailable")
    batch = _rand_batch(2, 500, 4)
    for kw in ({}, {"enable_hll": True, "hll_p": 8},
               {"distinct_keys_per_partition": True, "hll_p": 14},
               {"enable_quantiles": True, "quantiles_per_partition": True}):
        cfg = AnalyzerConfig(
            num_partitions=4, batch_size=500, wire_format=5, **kw
        )
        a = pack_batch(batch, cfg, use_native=False)
        b = pack_batch(batch, cfg, use_native=True)
        assert np.array_equal(a, b), kw
    # alive combo: pair order differs (sorted vs first-touch).  Pinned to
    # --alive-compaction off: this asserts the UNCOMPACTED v5 pair-section
    # layout (the compacted rows — no pair sections at all — are covered
    # by tests/test_alive_compaction.py).
    cfg = AnalyzerConfig(num_partitions=4, batch_size=500, wire_format=5,
                         count_alive_keys=True, alive_bitmap_bits=14,
                         alive_compaction="off")
    ua = unpack_numpy(pack_batch(batch, cfg, use_native=False).copy(), cfg)
    ub = unpack_numpy(pack_batch(batch, cfg, use_native=True).copy(), cfg)
    np_pairs = int(ua["n_pairs"])
    assert np_pairs == int(ub["n_pairs"])
    assert dict(zip(ua["alive_slot"][:np_pairs].tolist(),
                    ua["alive_flag"][:np_pairs].tolist())) == dict(
        zip(ub["alive_slot"][:np_pairs].tolist(),
            ub["alive_flag"][:np_pairs].tolist()))
    assert np.array_equal(np.asarray(ua["counts"]), np.asarray(ub["counts"]))


def test_v5_empty_batch_is_identity_pad():
    """A packed empty v5 batch is the superbatch identity pad: zero
    counter/quantile tables, identity-filled extremes."""
    cfg = _cfg(5)
    buf = pack_batch(RecordBatch.empty(0), cfg, use_native=False)
    got = unpack_numpy(buf, cfg)
    assert int(got["n_valid"]) == 0
    assert not np.asarray(got["counts"]).any()
    assert not np.asarray(got["qcounts"]).any()
    assert (np.asarray(got["ts_min"]) == np.iinfo(np.int64).max).all()
    assert (np.asarray(got["sz_max"]) == 0).all()


def test_section_byte_split_sums_to_packed_nbytes():
    for wf in (4, 5):
        for kw in ({}, {"count_alive_keys": True},
                   {"enable_quantiles": True, "quantiles_per_partition": True}):
            cfg = AnalyzerConfig(num_partitions=7, batch_size=256,
                                 wire_format=wf, **kw)
            per_rec, table = section_byte_split(cfg, 256)
            assert per_rec + table == packed_nbytes(cfg, 256), (wf, kw)
    # v5 without the alive pairs ships NO per-record bytes at all.
    cfg = AnalyzerConfig(num_partitions=7, batch_size=256, wire_format=5)
    per_rec, table = section_byte_split(cfg, 256)
    assert per_rec == 0 and table == packed_nbytes(cfg, 256)


def test_pallas_counters_merge_exact():
    """The v5 pallas table-merge (u32 digit planes + carry) is exact for
    adversarial i64 values — carries across the 2^32 boundary, negative
    sentinels, INT64 extremes."""
    from kafka_topic_analyzer_tpu.ops.pallas_counters import (
        pallas_counters_merge,
    )

    rng = np.random.default_rng(9)
    a = rng.integers(-2**62, 2**62, size=(37, 7), dtype=np.int64)
    b = rng.integers(-2**62, 2**62, size=(37, 7), dtype=np.int64)
    a[0, 0] = (1 << 32) - 1
    b[0, 0] = 1  # lo-word carry
    a[0, 1] = -1
    b[0, 1] = 1
    a[0, 2] = np.iinfo(np.int64).max
    b[0, 2] = np.iinfo(np.int64).min
    got = np.asarray(pallas_counters_merge(a, b))
    assert np.array_equal(got, a + b)


def test_ddsketch_edges_match_device_buckets():
    """The integer edge table and the device update agree on every bucket
    — including exact edge values, edge+1, and 0."""
    import jax

    from kafka_topic_analyzer_tpu.jax_support import jnp
    from kafka_topic_analyzer_tpu.ops.ddsketch import (
        ddsketch_bucket_numpy,
        ddsketch_edges,
        ddsketch_update,
    )

    gamma, nbuckets = (1.0 + 0.005) / (1.0 - 0.005), 2560
    edges = ddsketch_edges(gamma, nbuckets)
    probe = np.unique(np.concatenate([
        np.array([0, 1, 2, 3], dtype=np.int64),
        edges[:200], edges[:200] + 1,
        np.array([int(edges[-1]), int(edges[-1]) + 1], dtype=np.int64),
    ]))
    host = ddsketch_bucket_numpy(probe, gamma, nbuckets)
    counts = jnp.zeros((1, nbuckets + 2), dtype=jnp.int64)
    dev = np.asarray(jax.jit(
        lambda c, s: ddsketch_update(
            c, s, jnp.ones(len(probe), dtype=bool), gamma, nbuckets
        )
    )(counts, jnp.asarray(probe)))[0]
    ref = np.zeros(nbuckets + 2, dtype=np.int64)
    np.add.at(ref, host, 1)
    assert np.array_equal(dev, ref)


# ---------------------------------------------------------------------------
# gating, telemetry, stats


def test_env_kill_switch_forces_v4(monkeypatch):
    monkeypatch.setenv("KTA_WIRE_V4", "1")
    cfg = AnalyzerConfig(num_partitions=2, batch_size=64)
    assert cfg.wire_format == 4
    assert cfg.wire_v4_reason == "env-kill-switch"
    monkeypatch.delenv("KTA_WIRE_V4")
    assert AnalyzerConfig(num_partitions=2, batch_size=64).wire_format == 5
    explicit = AnalyzerConfig(num_partitions=2, batch_size=64, wire_format=4)
    assert explicit.wire_v4_reason == "explicit"
    with pytest.raises(ValueError, match="wire_format"):
        AnalyzerConfig(num_partitions=2, batch_size=64, wire_format=3)


def _metric_total(name: str) -> float:
    m = default_registry().snapshot().get(name)
    return sum(s["value"] for s in m["samples"]) if m else 0.0


def test_v4_fallback_booked_and_wire_bytes_counted():
    before_fb = _metric_total("kta_wire_v4_fallback_total")
    before_bytes = _metric_total("kta_wire_bytes_total")
    result = _wire_scan(4)
    assert _metric_total("kta_wire_v4_fallback_total") == before_fb + 1
    grew = _metric_total("kta_wire_bytes_total") - before_bytes
    assert grew > 0
    assert result.wire is not None
    assert result.wire.format == 4
    assert result.wire.bytes_total == int(grew)
    assert result.wire.records == N_PARTS * N_REC
    assert result.wire.bytes_per_record > 0


def test_stats_wire_line_renders():
    from kafka_topic_analyzer_tpu.report import render_telemetry_stats

    result = _wire_scan(5)
    text = render_telemetry_stats(
        result.telemetry, wire=result.wire,
    )
    assert "wire-format: v5" in text
    assert "fold-table" in text
    # v5's fold tables dominate this config's buffers (only the alive
    # pairs remain per-record).
    assert result.wire.table_bytes > 0


def test_scan_v5_with_native_disabled_subprocess():
    """KTA_DISABLE_NATIVE: the v5 scan runs the pure-python packers end to
    end — wire v5 is a layout, not a native-shim dependency."""
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu';"
        "from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec;"
        "from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend;"
        "from kafka_topic_analyzer_tpu.config import AnalyzerConfig;"
        "from kafka_topic_analyzer_tpu.engine import run_scan;"
        "spec = SyntheticSpec(num_partitions=2, messages_per_partition=50, keys_per_partition=9, seed=3);"
        "cfg = AnalyzerConfig(num_partitions=2, batch_size=32, enable_quantiles=True);"
        "assert cfg.wire_format == 5;"
        "r = run_scan('t', SyntheticSource(spec), TpuBackend(cfg, init_now_s=0, use_native=False), 32);"
        "assert r.metrics.overall_count == 100, r.metrics.overall_count;"
        "assert r.wire.format == 5"
    )
    env = dict(os.environ, KTA_DISABLE_NATIVE="1")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr
