"""Superbatch dispatch: scan-folded multi-batch device steps + the bounded
in-flight dispatch queue.

The tentpole contract (DESIGN.md §12): for any superbatch size K and
dispatch depth D, a scan's `ScanResult` — metrics, degraded/corrupt maps,
resume offsets — is byte-identical to the per-batch (K=1, D=1) scan of the
same topic.  That must hold composed with the resilience machinery of
earlier PRs (transport faults, deterministic corruption, parallel ingest),
with fold-consistent checkpoints (snapshots only at superbatch boundaries,
partial-tail flush on stop/fault), and across resume chains that change K.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.base import DispatchQueue
from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    CorruptionConfig,
    DispatchConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

from fake_broker import (
    ChaosTrigger,
    CorruptionInjector,
    FakeBroker,
    FaultInjector,
)

pytestmark = pytest.mark.superbatch

TOPIC = "superbatch.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 29}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


N_PARTS = 4
N_REC = 300
RECORDS = {p: _mk_records(p, N_REC) for p in range(N_PARTS)}

CFG = AnalyzerConfig(
    num_partitions=N_PARTS, batch_size=128,
    count_alive_keys=True, alive_bitmap_bits=16,
)

SPEC = SyntheticSpec(
    num_partitions=5, messages_per_partition=1000,
    keys_per_partition=31, tombstone_permille=120, seed=3,
)
SYN_CFG = AnalyzerConfig(
    num_partitions=5, batch_size=256,
    count_alive_keys=True, alive_bitmap_bits=16,
    enable_hll=True, hll_p=10, enable_quantiles=True,
)


def _backend(cfg=SYN_CFG, k=1, d=1):
    return TpuBackend(
        cfg, init_now_s=10**10,
        dispatch=DispatchConfig(superbatch=k, depth=d),
    )


def _full_doc(result) -> dict:
    return {
        "metrics": result.metrics.to_dict(
            result.start_offsets, result.end_offsets
        ),
        "start": result.start_offsets,
        "end": result.end_offsets,
        "degraded": result.degraded_partitions,
        "corrupt": result.corrupt_partitions,
    }


# ---------------------------------------------------------------------------
# unit: DispatchConfig sizing + the dispatch queue


def test_dispatch_config_parse_and_resolve():
    assert DispatchConfig.parse("4", 3) == DispatchConfig(superbatch=4, depth=3)
    # auto targets 2^20 records/dispatch but the round-7 guardrail caps the
    # synchronous fold at auto_fold_cap_records (2^18 by default): the
    # K=16 x B=2^16 e2e regression (0.63x, BENCH round 7) can no longer be
    # reached through auto.
    assert DispatchConfig.parse("auto").resolve(1 << 12) == 16
    assert DispatchConfig.parse("auto").resolve(1 << 14) == 16
    assert DispatchConfig.parse("auto").resolve(1 << 16) == 4
    assert DispatchConfig.parse("auto").resolve(1 << 18) == 1
    assert DispatchConfig.parse("auto").resolve(1 << 20) == 1
    assert DispatchConfig.parse("auto").resolve(1 << 22) == 1  # floor 1
    # A wider explicit cap restores the pure 2^20-records target...
    wide = DispatchConfig(superbatch="auto", auto_fold_cap_records=1 << 20)
    assert wide.resolve(1 << 16) == 16
    # ...and explicit K is never capped: the operator's number wins.
    assert DispatchConfig.parse("16").resolve(1 << 16) == 16
    assert DispatchConfig.parse("1").resolve(1 << 16) == 1
    with pytest.raises(ValueError):
        DispatchConfig.parse("0")
    with pytest.raises(ValueError):
        DispatchConfig.parse("lots")
    with pytest.raises(ValueError):
        DispatchConfig(superbatch=2, depth=0)
    with pytest.raises(ValueError):
        DispatchConfig(superbatch=2, auto_fold_cap_records=0)


class _Tok:
    """Completion-token double: not ready until something blocks on it
    (the jax.block_until_ready duck-type protocol)."""

    def __init__(self):
        self.ready = False

    def is_ready(self):
        return self.ready

    def block_until_ready(self):
        self.ready = True
        return self


def test_dispatch_queue_bounds_inflight():
    from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

    q = DispatchQueue(2)
    t1, t2, t3 = _Tok(), _Tok(), _Tok()
    q.throttle(); q.launched(t1, 4)
    q.throttle(); q.launched(t2, 4)
    assert len(q) == 2
    # At the bound: throttle must BLOCK on (and retire) the oldest before
    # a third launch may record — the drive loop's memory guarantee.
    q.throttle()
    assert t1.ready and len(q) == 1
    q.launched(t3, 2)
    q.drain()
    assert t2.ready and t3.ready and len(q) == 0
    assert obs_metrics.DISPATCH_INFLIGHT.value == 0
    with pytest.raises(ValueError):
        DispatchQueue(0)


def test_backend_rejects_oversized_superbatch():
    be = _backend(k=2, d=1)
    batches = list(SyntheticSource(SPEC).batches(256))
    with pytest.raises(ValueError):
        be.update_superbatch(batches[:3])
    with pytest.raises(ValueError):
        be.update_superbatch([])


# ---------------------------------------------------------------------------
# determinism: every (K, D) == the K=1 per-batch scan, byte for byte


@pytest.fixture(scope="module")
def syn_baseline():
    """Per-batch (K=1) synthetic scan — the byte-exact referee."""
    r = run_scan("t", SyntheticSource(SPEC), _backend(), 256)
    assert r.superbatch_k == 1
    return _full_doc(r)


@pytest.mark.parametrize("k", [2, 4, 8])
@pytest.mark.parametrize("d", [1, 2, 4])
def test_k_by_d_byte_identical(syn_baseline, k, d):
    # 20 batches per scan: K=8 exercises a partial (identity-padded) tail,
    # K∈{2,4} exact multiples — both must match the referee exactly.
    r = run_scan("t", SyntheticSource(SPEC), _backend(k=k, d=d), 256)
    assert (r.superbatch_k, r.dispatch_depth) == (k, d)
    assert _full_doc(r) == syn_baseline


def test_superbatch_composes_with_parallel_ingest(syn_baseline):
    """PR-4 composition: N ingest workers feeding the accumulate-K loop
    (staged host buffers routed through the fan-in) changes nothing."""
    r = run_scan(
        "t", SyntheticSource(SPEC), _backend(k=4, d=2), 256,
        ingest_workers=3,
    )
    assert r.ingest_workers == 3
    assert _full_doc(r) == syn_baseline


def test_single_batch_topic_partial_superbatch(syn_baseline):
    """K far beyond the batch count: the whole scan is one partial tail."""
    r = run_scan("t", SyntheticSource(SPEC), _backend(k=16, d=2), 256)
    assert _full_doc(r) == syn_baseline


# ---------------------------------------------------------------------------
# fault composition: chaos + corruption landing mid-superbatch


@pytest.fixture(scope="module")
def wire_baseline():
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        result = run_scan(
            TOPIC, src, TpuBackend(CFG, init_now_s=10**10), 128
        )
        src.close()
    assert not result.degraded_partitions
    return _full_doc(result)


def test_transport_fault_mid_superbatch_absorbed(wire_baseline):
    """A connection kill lands while a superbatch is accumulating; retry +
    recovery must keep the K=4 result byte-identical to per-batch."""
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        trigger = ChaosTrigger(
            src, 2,
            lambda: setattr(
                broker, "faults",
                FaultInjector().drop_connection(100, times=2),
            ),
        )
        result = run_scan(
            TOPIC, trigger, TpuBackend(CFG, init_now_s=10**10, dispatch=DispatchConfig(superbatch=4, depth=2)),
            128,
        )
        src.close()
    assert not result.degraded_partitions
    assert _full_doc(result) == wire_baseline


def test_corruption_mid_superbatch_matches_per_batch(tmp_path):
    """Deterministic poison under --on-corruption=quarantine: the corrupt
    accounting map, metrics, and quarantine spool all match K=1."""

    def poisoned():
        inj = (
            CorruptionInjector()
            .flip_byte(1, chunk=1, offset=-1)
            .flip_byte(1, chunk=3, offset=-3)
        )
        return FakeBroker(
            TOPIC, RECORDS, max_records_per_fetch=50, corruption=inj,
            honor_partition_max_bytes=True,
        )

    def run(k, d, qdir):
        with poisoned() as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC,
                overrides=dict(FAST_RETRY, **{"check.crcs": "true"}),
                corruption=CorruptionConfig(
                    policy="quarantine", quarantine_dir=qdir
                ),
            )
            result = run_scan(
                TOPIC, src,
                TpuBackend(CFG, init_now_s=10**10,
                           dispatch=DispatchConfig(superbatch=k, depth=d)),
                128,
            )
            src.close()
        return result

    seq = run(1, 1, str(tmp_path / "q1"))
    sup = run(4, 2, str(tmp_path / "q4"))
    assert set(seq.corrupt_partitions) == {1}
    assert _full_doc(sup) == _full_doc(seq)
    assert sorted(os.listdir(tmp_path / "q4")) == sorted(
        os.listdir(tmp_path / "q1")
    )


# ---------------------------------------------------------------------------
# checkpoints: boundary-only snapshots, partial-tail flush, any-K resume


def _snapshot_seqs(monkeypatch):
    """Record every save_snapshot call's records_seen (in call order)."""
    from kafka_topic_analyzer_tpu import checkpoint

    seen = []
    real = checkpoint.save_snapshot

    def spy(*args, **kwargs):
        seen.append(args[5] if len(args) > 5 else kwargs["records_seen"])
        return real(*args, **kwargs)

    monkeypatch.setattr(checkpoint, "save_snapshot", spy)
    return seen


def test_snapshots_land_only_at_superbatch_boundaries(tmp_path, monkeypatch):
    """With a zero snapshot interval the per-batch scan snapshots after
    every batch; the K=4 scan may only snapshot at superbatch boundaries —
    every 4th batch's cumulative count, plus the flushed tail."""
    seqs = _snapshot_seqs(monkeypatch)
    run_scan(
        "t", SyntheticSource(SPEC), _backend(), 256,
        snapshot_dir=str(tmp_path / "k1"), snapshot_every_s=0.0,
    )
    per_batch = list(seqs)
    assert per_batch  # one per batch
    seqs.clear()
    run_scan(
        "t", SyntheticSource(SPEC), _backend(k=4, d=2), 256,
        snapshot_dir=str(tmp_path / "k4"), snapshot_every_s=0.0,
    )
    boundaries = per_batch[3::4]
    if per_batch[-1] not in boundaries:
        boundaries.append(per_batch[-1])  # the partial-tail flush
    assert seqs == boundaries


def test_final_snapshot_identical_across_k(tmp_path):
    def snap_meta(k, d):
        with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            )
            run_scan(
                TOPIC, src,
                TpuBackend(CFG, init_now_s=10**10,
                           dispatch=DispatchConfig(superbatch=k, depth=2)),
                128, snapshot_dir=str(d), snapshot_every_s=0.0,
            )
            src.close()
        with np.load(
            os.path.join(str(d), "scan_snapshot.npz"), allow_pickle=False
        ) as z:
            meta = json.loads(str(z["__meta__"]))
        return meta["next_offsets"], meta["records_seen"]

    assert snap_meta(1, tmp_path / "k1") == snap_meta(4, tmp_path / "k4")


class _Interrupt(Exception):
    pass


class _InterruptingSource(SyntheticSource):
    """Raises after `limit` batches — a crash landing mid-superbatch."""

    def __init__(self, spec, limit):
        super().__init__(spec)
        self.limit = limit

    def batches(self, batch_size, partitions=None, start_at=None):
        it = super().batches(batch_size, partitions, start_at)
        for i, b in enumerate(it):
            if start_at is None and i >= self.limit:
                raise _Interrupt()
            yield b


def test_fault_flushes_partial_tail_and_resumes_across_k(tmp_path):
    """A crash with 3 batches pending (K=4, 7 batches seen) must flush the
    partial tail before the failure snapshot — every observed batch folded
    and committed, exactly the per-batch path's invariant — and the resume
    may run under a DIFFERENT K and still reproduce the clean scan."""
    full = run_scan("t", SyntheticSource(SPEC), _backend(), 256).metrics

    be1 = _backend(k=4, d=2)
    with pytest.raises(_Interrupt):
        run_scan(
            "t", _InterruptingSource(SPEC, limit=7), be1, 256,
            snapshot_dir=str(tmp_path), snapshot_every_s=3600.0,
        )
    from kafka_topic_analyzer_tpu.checkpoint import load_snapshot

    snap = load_snapshot(
        str(tmp_path), "t", SYN_CFG, template=be1.get_state()
    )
    assert snap is not None
    # All 7 observed batches committed: 4 from the full superbatch, 3 from
    # the fault-path partial flush.
    assert snap[2] == 7 * 256

    be2 = TpuBackend(
        SYN_CFG, init_now_s=0, dispatch=DispatchConfig(superbatch=3, depth=1)
    )
    result = run_scan(
        "t", SyntheticSource(SPEC), be2, 256,
        snapshot_dir=str(tmp_path), resume=True,
    )
    assert result.metrics.to_dict(
        result.start_offsets, result.end_offsets
    ) == full.to_dict(result.start_offsets, result.end_offsets)


# ---------------------------------------------------------------------------
# queue mechanics: error propagation, close-on-exit, no leaks


class _Boom(Exception):
    pass


class _ExplodingSource(SyntheticSource):
    def __init__(self, spec, bad_partition):
        super().__init__(spec)
        self.bad = bad_partition

    def batches(self, batch_size, partitions=None, start_at=None):
        it = super().batches(batch_size, partitions, start_at)
        if partitions is None or self.bad not in partitions:
            yield from it
            return
        for i, b in enumerate(it):
            if i >= 2:
                raise _Boom()
            yield b


def test_worker_error_aborts_superbatch_scan_without_leaks():
    """An ingest-worker death mid-accumulation: the scan aborts, the
    fault path flushes what it can, and no worker threads leak."""
    spec = SyntheticSpec(num_partitions=4, messages_per_partition=4000)
    cfg = AnalyzerConfig(num_partitions=4, batch_size=128)
    before = threading.active_count()
    with pytest.raises(_Boom):
        run_scan(
            "t", _ExplodingSource(spec, bad_partition=1),
            TpuBackend(cfg, init_now_s=0,
                       dispatch=DispatchConfig(superbatch=4, depth=2)),
            128, ingest_workers=3,
        )
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_dispatch_telemetry_recorded():
    from kafka_topic_analyzer_tpu.obs.registry import default_registry

    def agg(snapshot, name):
        metric = snapshot.get(name) or {"samples": []}
        return sum(s.get("count", 0) for s in metric["samples"])

    before = default_registry().snapshot()
    result = run_scan("t", SyntheticSource(SPEC), _backend(k=4, d=2), 256)
    # 20 batches at K=4 → exactly 5 dispatches, each with a latency sample.
    dispatches = agg(result.telemetry, "kta_superbatch_size") - agg(
        before, "kta_superbatch_size"
    )
    latencies = agg(result.telemetry, "kta_dispatch_seconds") - agg(
        before, "kta_dispatch_seconds"
    )
    assert dispatches == 5
    assert latencies == 5


# ---------------------------------------------------------------------------
# sharded mesh: the scanned collective step


@pytest.mark.parametrize("mesh_shape", [(2, 1), (2, 2)])
def test_sharded_superbatch_byte_identical(mesh_shape):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg = AnalyzerConfig(
        num_partitions=5, batch_size=256,
        count_alive_keys=True, alive_bitmap_bits=16,
        enable_hll=True, hll_p=10, mesh_shape=mesh_shape,
    )

    def doc(k, d):
        be = ShardedTpuBackend(
            cfg, init_now_s=10**10,
            dispatch=DispatchConfig(superbatch=k, depth=d),
        )
        r = run_scan("t", SyntheticSource(SPEC), be, 256)
        return _full_doc(r)

    ref = doc(1, 1)
    for k, d in [(2, 1), (4, 2)]:
        assert doc(k, d) == ref


# ---------------------------------------------------------------------------
# CLI surface


def test_cli_superbatch_json_and_stats(capsys):
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "t", "--source", "synthetic",
        "--synthetic", "partitions=4,messages=2000",
        "--backend", "tpu", "--batch-size", "512",
        "--superbatch", "4", "--dispatch-depth", "2",
        "--stats", "--json", "--quiet",
    ])
    assert rc == 0
    out = capsys.readouterr()
    doc = json.loads(out.out.splitlines()[-1])
    assert doc["superbatch_k"] == 4
    assert doc["dispatch_depth"] == 2
    assert "superbatch dispatches (K=4, depth=2)" in out.err


def test_cli_rejects_superbatch_on_cpu_backend(capsys):
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "t", "--source", "synthetic",
        "--synthetic", "partitions=4,messages=100",
        "--backend", "cpu", "--superbatch", "4", "--quiet",
    ])
    assert rc == 1
    assert "--backend tpu" in capsys.readouterr().err


def test_cli_superbatch_auto_on_cpu_backend_is_noop(capsys):
    """'auto' means "size appropriately" — on the cpu oracle that is no
    superbatching, not an error (mirrors --ingest-workers auto under a
    mesh: host-dependent hard errors would pass CI and fail prod)."""
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "t", "--source", "synthetic",
        "--synthetic", "partitions=4,messages=100",
        "--backend", "cpu", "--superbatch", "auto", "--json", "--quiet",
    ])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert doc["superbatch_k"] == 1


def test_cli_rejects_bad_superbatch_spec(capsys):
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "t", "--source", "synthetic",
        "--synthetic", "partitions=4,messages=100",
        "--backend", "tpu", "--superbatch", "many", "--quiet",
    ])
    assert rc == 1
    assert "--superbatch" in capsys.readouterr().err
