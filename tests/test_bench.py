"""bench.py output contract: honest degraded reporting (VERDICT r2 weak #5)
and setup-phase error messages that name the offending key (weak #3)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_degraded_bench_nulls_vs_baseline():
    """A host-CPU fallback run must not print a headline vs_baseline ratio:
    13.66x-on-a-CPU reads as the result at a glance.  The ratio moves to
    vs_baseline_on_fallback_host; vs_baseline goes null."""
    env = dict(os.environ)
    env["KTA_BENCH_CHILD"] = "1"   # run main() directly, no supervisor
    env["KTA_ACCEL_OK"] = "1"      # skip the probe; JAX_PLATFORMS=cpu is
    env["JAX_PLATFORMS"] = "cpu"   # honored by the short-circuit fix
    env.pop("KTA_JAX_PLATFORMS", None)  # an explicit override would read
    #                                     as deliberate, not degraded
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--batch-size", "2048", "--batches", "2", "--steps", "4",
         "--partitions", "4", "--features", "counters"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, proc.stdout
    doc = json.loads(lines[-1])
    assert doc["degraded_cpu_fallback"] is True
    assert doc["vs_baseline"] is None
    assert doc["vs_baseline_on_fallback_host"] > 0
    assert doc["platform"] == "cpu"


def test_accuracy_seed_referee_matches_main_run_cardinality():
    """The per-seed sketch-error referee must run at the SAME dataset size
    as the main draw (HLL error depends on cardinality — r4 weak #5), and
    the recorded JSON must say what N each seed used."""
    env = dict(os.environ)
    env["KTA_BENCH_CHILD"] = "1"
    env["KTA_ACCEL_OK"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--batch-size", "2048", "--batches", "6", "--steps", "6",
         "--partitions", "4", "--features", "counters,hll",
         "--keys", "5000", "--accuracy", "--accuracy-seeds", "1"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    doc = json.loads(lines[-1])
    # default: per-seed batch count == the main run's --batches (not a cap)
    assert doc["accuracy_seed_batches"] == 6
    assert doc["accuracy_seed_records"] == 6 * 2048
    assert len(doc["hll_rel_error_seeds"]) == 1


def test_synthetic_kv_errors_name_the_key():
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSpec

    with pytest.raises(ValueError, match=r"key_null.*per-mille.*'0\.05'"):
        SyntheticSpec.from_kv({"key_null": "0.05"})
    with pytest.raises(ValueError, match=r"'tombstones'.*per-mille"):
        SyntheticSpec.from_kv({"tombstones": "1500"})  # out of 0..1000
    with pytest.raises(ValueError, match=r"unknown --synthetic key 'mesages'"):
        SyntheticSpec.from_kv({"mesages": "10"})
    with pytest.raises(ValueError, match=r"'partitions'.*integer.*'two'"):
        SyntheticSpec.from_kv({"partitions": "two"})
    with pytest.raises(ValueError, match=r"'partitions'.*positive"):
        SyntheticSpec.from_kv({"partitions": "0"})
    with pytest.raises(ValueError, match=r"'keys'.*positive"):
        SyntheticSpec.from_kv({"keys": "0"})
    with pytest.raises(ValueError, match=r"'vmax'.*>= vmin"):
        SyntheticSpec.from_kv({"vmin": "400", "vmax": "100"})
    # vmin alone above the default vmax means fixed-size values, not an error
    spec = SyntheticSpec.from_kv({"vmin": "500"})
    assert (spec.value_len_min, spec.value_len_max) == (500, 500)
    # trailing comma (empty key) stays accepted
    SyntheticSpec.from_kv({"partitions": "2", "": ""})
    # hex seeds stay accepted
    assert SyntheticSpec.from_kv({"seed": "0x10"}).seed == 0x10


def test_cli_reports_synthetic_kv_error_cleanly(capsys):
    from kafka_topic_analyzer_tpu.cli import main

    rc = main([
        "-t", "t", "--source", "synthetic",
        "--synthetic", "key_null=0.05", "--quiet", "--native", "off",
    ])
    assert rc == 1
    err = capsys.readouterr().err
    assert "key_null" in err and "per-mille" in err and "0.05" in err
    assert "Traceback" not in err


def test_mesh_parse_error_names_the_flag():
    from kafka_topic_analyzer_tpu.cli import parse_mesh

    with pytest.raises(ValueError, match=r"--mesh '4x2'.*device"):
        parse_mesh("4x2")
    with pytest.raises(ValueError, match=r"--mesh '1,2,3'"):
        parse_mesh("1,2,3")
    with pytest.raises(ValueError, match=r"--mesh '0'.*positive"):
        parse_mesh("0")
    with pytest.raises(ValueError, match=r"--mesh '-4,2'.*positive"):
        parse_mesh("-4,2")


@pytest.mark.ingest
def test_bench_ingest_workers_smoke(capsys):
    """--workers N drives the real fan-in over a loopback broker and
    reports aggregate + per-worker rates."""
    import json as _json

    from kafka_topic_analyzer_tpu.tools import bench_ingest

    rc = bench_ingest.main([
        "--records", "120000", "--records-per-batch", "512",
        "--partitions", "4", "--batch-size", "4096",
        "--repeat", "1", "--skip-drain", "--workers", "2",
    ])
    assert rc == 0
    doc = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["workers"] == 2
    assert doc["scan_msgs_per_sec"] > 0
    assert set(doc["scan_worker_records"]) == {"0", "1"}
    # windows = 120000 // (4 partitions * 512 rpb) = 58 -> 58*512*4 records
    assert sum(doc["scan_worker_records"].values()) == 58 * 512 * 4
