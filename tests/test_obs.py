"""Unit + property coverage for the obs telemetry subsystem: registry
instruments and snapshot/merge algebra, Prometheus text exposition, the
event bus + JSONL sink, the span tracer, and the scrape endpoint.

The histogram merge law — merging N shard snapshots equals observing the
union of their samples — is the contract multi-controller aggregation
(parallel/sharded.py::gather_telemetry) leans on; it gets a hypothesis
property test (skipped cleanly when hypothesis is absent, like
test_properties.py)."""

import json
import math
import urllib.error
import urllib.request

import pytest

from kafka_topic_analyzer_tpu.obs import events, trace
from kafka_topic_analyzer_tpu.obs.exporters import CONTENT_TYPE, PrometheusExporter
from kafka_topic_analyzer_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    merge_snapshots,
    render_prometheus,
)

# ---------------------------------------------------------------------------
# instruments


def test_counter_monotonic():
    c = Counter("c_total", "help")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc():
    g = Gauge("g", "help")
    g.set(7)
    g.inc(3)
    assert g.value == 10.0


def test_histogram_bucket_placement():
    h = Histogram("h", "help", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 4.0, 5.0):
        h.observe(v)
    s = h.samples()[0]
    # le is inclusive (Prometheus contract): 1.0 lands in le=1, 4.0 in le=4.
    assert s["counts"] == [2, 1, 1, 1]
    assert s["count"] == 5
    assert s["sum"] == pytest.approx(12.0)


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        Histogram("h", "help", buckets=(2.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("h", "help", buckets=(1.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("h", "help", buckets=())


def test_histogram_time_context():
    h = Histogram("h", "help", buckets=(10.0,))
    with h.time():
        pass
    s = h.samples()[0]
    assert s["count"] == 1
    assert 0 <= s["sum"] < 10.0


def test_labels_children_and_validation():
    c = Counter("c_total", "help", labelnames=("partition",))
    c.labels(0).inc()
    c.labels(partition=0).inc()
    c.labels("1").inc(5)
    by = {tuple(s["labels"].items()): s["value"] for s in c.samples()}
    assert by[(("partition", "0"),)] == 2.0
    assert by[(("partition", "1"),)] == 5.0
    with pytest.raises(ValueError):
        c.labels("0", "extra")
    with pytest.raises(ValueError):
        Counter("c", "help", labelnames=("bad-name",))


def test_registry_get_or_create_and_conflicts():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "help")
    assert reg.counter("x_total", "other help") is a
    with pytest.raises(ValueError):
        reg.gauge("x_total", "now a gauge?")
    with pytest.raises(ValueError):
        reg.counter("bad name", "help")


def test_registry_reset_keeps_registrations():
    reg = MetricsRegistry()
    c = reg.counter("x_total", "help")
    h = reg.histogram("h", "help", buckets=(1.0,))
    c.inc(3)
    h.observe(0.5)
    reg.reset()
    assert c.value == 0.0
    assert reg.counter("x_total", "help") is c
    assert h.samples()[0]["count"] == 0


# ---------------------------------------------------------------------------
# Prometheus text exposition


def test_render_prometheus_counter_and_histogram():
    reg = MetricsRegistry()
    reg.counter("kta_x_total", "records\nseen").inc(3)
    h = reg.histogram("kta_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = render_prometheus(reg.snapshot())
    assert "# HELP kta_x_total records seen\n" in text  # newline escaped
    assert "# TYPE kta_x_total counter\n" in text
    assert "kta_x_total 3\n" in text
    assert 'kta_lat_seconds_bucket{le="0.1"} 1\n' in text
    assert 'kta_lat_seconds_bucket{le="1"} 2\n' in text
    assert 'kta_lat_seconds_bucket{le="+Inf"} 3\n' in text  # cumulative
    assert "kta_lat_seconds_count 3\n" in text


def test_render_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.gauge("g", "help", labelnames=("t",)).labels('a"b\\c\nd').set(1)
    text = render_prometheus(reg.snapshot())
    assert 'g{t="a\\"b\\\\c\\nd"} 1' in text


# ---------------------------------------------------------------------------
# merge algebra


def _snap(build):
    reg = MetricsRegistry()
    build(reg)
    return reg.snapshot()


def test_merge_counters_add_gauges_max():
    a = _snap(lambda r: (r.counter("c_total", "h").inc(2),
                         r.gauge("g", "h").set(5)))
    b = _snap(lambda r: (r.counter("c_total", "h").inc(3),
                         r.gauge("g", "h").set(4)))
    merged = merge_snapshots([a, b])
    assert merged["c_total"]["samples"][0]["value"] == 5.0
    assert merged["g"]["samples"][0]["value"] == 5.0


def test_merge_sum_policy_gauge():
    # Disjoint per-process counts (e.g. locally-degraded partitions)
    # declare merge="sum"; the policy rides in the snapshot.
    a = _snap(lambda r: r.gauge("deg", "h", merge="sum").set(2))
    b = _snap(lambda r: r.gauge("deg", "h", merge="sum").set(3))
    merged = merge_snapshots([a, b])
    assert merged["deg"]["samples"][0]["value"] == 5.0
    assert a["deg"]["merge"] == "sum"
    with pytest.raises(ValueError):
        MetricsRegistry().gauge("bad", "h", merge="median")


def test_merge_disjoint_labels_union():
    a = _snap(lambda r: r.gauge("lag", "h", labelnames=("p",)).labels(0).set(10))
    b = _snap(lambda r: r.gauge("lag", "h", labelnames=("p",)).labels(1).set(20))
    merged = merge_snapshots([a, b])
    assert [
        (s["labels"]["p"], s["value"]) for s in merged["lag"]["samples"]
    ] == [("0", 10.0), ("1", 20.0)]


def test_merge_histogram_bucket_mismatch_raises():
    a = _snap(lambda r: r.histogram("h", "h", buckets=(1.0, 2.0)).observe(1))
    b = _snap(lambda r: r.histogram("h", "h", buckets=(1.0, 4.0)).observe(1))
    with pytest.raises(ValueError, match="bucket layouts"):
        merge_snapshots([a, b])


def test_merge_type_conflict_raises():
    a = _snap(lambda r: r.counter("m_total", "h").inc())
    b = _snap(lambda r: r.gauge("m_total", "h").set(1))
    with pytest.raises(ValueError, match="conflicting types"):
        merge_snapshots([a, b])


def test_merge_does_not_mutate_inputs():
    a = _snap(lambda r: r.counter("c_total", "h").inc(1))
    b = _snap(lambda r: r.counter("c_total", "h").inc(2))
    merge_snapshots([a, b])
    merge_snapshots([a, b])
    assert a["c_total"]["samples"][0]["value"] == 1.0


def test_merge_n_shards_equals_observing_union_property():
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    buckets = (0.001, 0.01, 0.1, 1.0, 10.0)
    samples_strategy = st.lists(
        st.lists(
            st.floats(
                min_value=0.0, max_value=100.0,
                allow_nan=False, allow_infinity=False,
            ),
            max_size=30,
        ),
        min_size=1,
        max_size=6,
    )

    @settings(max_examples=60, deadline=None)
    @given(shards=samples_strategy)
    def law(shards):
        snaps = []
        for values in shards:
            reg = MetricsRegistry()
            h = reg.histogram("h", "help", buckets=buckets)
            c = reg.counter("n_total", "help")
            for v in values:
                h.observe(v)
                c.inc()
            snaps.append(reg.snapshot())
        union_reg = MetricsRegistry()
        uh = union_reg.histogram("h", "help", buckets=buckets)
        uc = union_reg.counter("n_total", "help")
        for values in shards:
            for v in values:
                uh.observe(v)
                uc.inc()
        merged = merge_snapshots(snaps)
        want = union_reg.snapshot()
        got_h = merged["h"]["samples"][0]
        want_h = want["h"]["samples"][0]
        assert got_h["counts"] == want_h["counts"]
        assert got_h["count"] == want_h["count"]
        assert got_h["sum"] == pytest.approx(want_h["sum"])
        assert (
            merged["n_total"]["samples"][0]["value"]
            == want["n_total"]["samples"][0]["value"]
        )

    law()


# ---------------------------------------------------------------------------
# event bus


def test_emit_without_sinks_is_noop():
    events.emit("anything", x=1)  # must not raise, must not allocate sinks


def test_jsonl_sink_and_capture(tmp_path):
    path = tmp_path / "events.jsonl"
    clock = iter([10.0, 11.5])
    sink = events.JsonlEventLog(str(path), clock=lambda: next(clock))
    events.add_sink(sink)
    try:
        events.emit("scan_start", topic="t", partitions=3)
        events.emit("scan_end", topic="t", records=5)
    finally:
        events.remove_sink(sink)
        sink.close()
    docs = [json.loads(line) for line in path.read_text().splitlines()]
    assert [d["type"] for d in docs] == ["scan_start", "scan_end"]
    assert docs[0]["ts"] == 10.0
    assert docs[0]["partitions"] == 3
    assert docs[1]["records"] == 5


def test_failing_sink_is_detached():
    calls = []

    def bad(etype, fields):
        calls.append(etype)
        raise RuntimeError("disk full")

    events.add_sink(bad)
    try:
        events.emit("one")
        events.emit("two")  # bad sink already detached; no raise
    finally:
        events.remove_sink(bad)
    assert calls == ["one"]


def test_heartbeat_rate_limit_and_force():
    t = [0.0]
    hb = events.Heartbeat(10.0, clock=lambda: t[0])
    assert hb.ready()
    t[0] = 5.0
    assert not hb.ready()
    t[0] = 10.0
    assert hb.ready()
    t[0] = 11.0
    hb.force()
    assert hb.ready()


# ---------------------------------------------------------------------------
# span tracer


def test_tracer_spans_and_chrome_format(tmp_path):
    t = [0.0]
    tr = trace.SpanTracer(clock=lambda: t[0])
    with tr.span("fetch", cat="io"):
        t[0] += 0.25
    tr.add_complete("decode", 1.0, 0.5, cat="io", args={"n": 3})
    doc = tr.chrome_trace()
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    ev = {e["name"]: e for e in doc["traceEvents"]}
    assert ev["fetch"]["ph"] == "X"
    assert ev["fetch"]["dur"] == pytest.approx(0.25e6)
    assert ev["decode"]["ts"] == pytest.approx(1.0e6)
    assert ev["decode"]["args"] == {"n": 3}
    path = tmp_path / "trace.json"
    tr.write(str(path))
    assert json.loads(path.read_text())["traceEvents"]


def test_maybe_span_active_and_inactive():
    with trace.maybe_span("idle"):
        pass  # no active tracer: a pure no-op
    tr = trace.SpanTracer()
    trace.set_active(tr)
    try:
        with trace.maybe_span("work"):
            pass
    finally:
        trace.set_active(None)
    assert [e["name"] for e in tr.events()] == ["work"]


def test_telemetry_session_bad_trace_path_fails_fast(tmp_path):
    from kafka_topic_analyzer_tpu.obs import telemetry_session

    with pytest.raises(OSError):
        with telemetry_session(trace_json=str(tmp_path / "no" / "t.json")):
            raise AssertionError("session body must not run")


def test_telemetry_session_write_failure_does_not_mask(tmp_path):
    from kafka_topic_analyzer_tpu.obs import telemetry_session

    trace_path = tmp_path / "t.json"
    events_path = tmp_path / "e.jsonl"

    class Boom(RuntimeError):
        pass

    # The scan's own exception must survive a failing trace write at
    # teardown (the path turns into a directory mid-session), and the
    # event sink must still be detached.
    with pytest.raises(Boom):
        with telemetry_session(
            events_jsonl=str(events_path), trace_json=str(trace_path)
        ):
            trace_path.unlink()
            trace_path.mkdir()
            raise Boom()
    assert events._sinks == []


# ---------------------------------------------------------------------------
# scrape endpoint


def test_prometheus_exporter_serves_registry():
    reg = MetricsRegistry()
    reg.counter("kta_test_total", "scrape me").inc(7)
    exporter = PrometheusExporter(0, registry=reg)
    try:
        url = f"http://127.0.0.1:{exporter.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = resp.read().decode()
            assert resp.headers["Content-Type"] == CONTENT_TYPE
        assert "kta_test_total 7\n" in body
        reg.counter("kta_test_total", "").inc()  # live: next scrape moves
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert "kta_test_total 8\n" in resp.read().decode()
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/nope", timeout=5
            )
    finally:
        exporter.close()


# ---------------------------------------------------------------------------
# thread-safety under N ingest workers (parallel/ingest.py)


@pytest.mark.ingest
def test_registry_hammer_no_lost_updates():
    """The multi-writer contract the parallel-ingest workers lean on:
    concurrent inc/observe/labels() from N threads lose NOTHING — every
    instrument's numeric state is guarded by its own lock, and the
    lock-free labeled-child fast path never hands two threads distinct
    children for the same label set."""
    import threading

    reg = MetricsRegistry()
    counter = reg.counter("h_total", "hammered counter")
    labeled = reg.counter("h_by_worker_total", "per-worker", labelnames=("w",))
    gauge = reg.gauge("h_gauge", "hammered gauge")
    hist = reg.histogram("h_hist", "hammered histogram", buckets=(1.0, 10.0))

    N_THREADS, N_OPS = 8, 5_000
    start = threading.Barrier(N_THREADS)
    children = [None] * N_THREADS

    def worker(t: int) -> None:
        start.wait()
        for i in range(N_OPS):
            counter.inc()
            labeled.labels(w=t % 2).inc(2)
            gauge.inc(1)
            hist.observe(float(i % 20))
        # Same label values from every thread must resolve to ONE child.
        children[t] = labeled.labels(w=t % 2)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(N_THREADS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert counter.value == N_THREADS * N_OPS
    assert gauge.value == N_THREADS * N_OPS
    snap = reg.snapshot()
    by_w = {
        s["labels"]["w"]: s["value"]
        for s in snap["h_by_worker_total"]["samples"]
    }
    assert by_w == {"0": 2 * (N_THREADS // 2) * N_OPS,
                    "1": 2 * (N_THREADS // 2) * N_OPS}
    h = snap["h_hist"]["samples"][0]
    assert h["count"] == N_THREADS * N_OPS
    assert sum(h["counts"]) == N_THREADS * N_OPS
    assert children[0] is children[2]  # fast path: one child per label set


@pytest.mark.ingest
def test_concurrent_scrape_during_writer_hammer():
    """The EXPOSITION path racing live registry writes — satellite of
    ISSUE 10: the 8-thread hammer above covers instrument mutation, but a
    Prometheus scrape walks instruments(), samples() and render while N
    ingest workers are concurrently inc-ing AND creating new labeled
    children (new workers appear mid-scan on sharded pools).  Every
    scrape must return 200 with parseable, internally-consistent text —
    no torn lines, no KeyError from a half-registered child, no lost
    bucket rows."""
    import re
    import threading

    reg = MetricsRegistry()
    counter = reg.counter("s_total", "scrape-raced counter")
    labeled = reg.counter("s_by_worker_total", "per-worker",
                          labelnames=("w",))
    hist = reg.histogram("s_hist", "scrape-raced histogram",
                         buckets=(1.0, 10.0))
    exporter = PrometheusExporter(0, registry=reg)
    url = f"http://127.0.0.1:{exporter.port}/metrics"

    N_WRITERS, N_OPS = 8, 2_000
    stop = threading.Event()
    errors: "list[BaseException]" = []
    start = threading.Barrier(N_WRITERS + 3)

    def writer(t: int) -> None:
        start.wait()
        try:
            for i in range(N_OPS):
                counter.inc()
                # Fresh label values appear DURING scrapes: child
                # creation races the exposition walk.
                labeled.labels(w=f"{t}.{i % 50}").inc()
                hist.observe(float(i % 20))
        except BaseException as e:  # pragma: no cover - failure capture
            errors.append(e)
        finally:
            stop.set()  # first finisher lets scrapers wind down

    scrapes: "list[str]" = []

    def scraper() -> None:
        start.wait()
        try:
            while not stop.is_set() or len(scrapes) < 5:
                with urllib.request.urlopen(url, timeout=5) as resp:
                    assert resp.status == 200
                    scrapes.append(resp.read().decode())
                if len(scrapes) > 200:
                    break
        except BaseException as e:  # pragma: no cover - failure capture
            errors.append(e)

    threads = [
        threading.Thread(target=writer, args=(t,))
        for t in range(N_WRITERS)
    ] + [threading.Thread(target=scraper) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        exporter.close()

    assert not errors, errors
    assert len(scrapes) >= 5
    line_re = re.compile(
        r"^(# (HELP|TYPE) \S.*|[a-zA-Z_:][a-zA-Z0-9_:]*"
        r'(\{[^{}]*\})? -?[0-9.e+Inf]+)$'
    )
    for body in scrapes:
        lines = body.rstrip("\n").split("\n")
        for ln in lines:
            assert line_re.match(ln), f"torn exposition line: {ln!r}"
        # Histogram internal consistency per scrape: the +Inf cumulative
        # bucket equals the count line that follows it.
        m_inf = re.search(r's_hist_bucket{le="\+Inf"} (\d+)', body)
        m_count = re.search(r"s_hist_count (\d+)", body)
        assert m_inf and m_count
        assert m_inf.group(1) == m_count.group(1)
    # Nothing lost under concurrent exposition: the post-join snapshot
    # carries every write.
    final = reg.snapshot()
    assert final["s_total"]["samples"][0]["value"] == N_WRITERS * N_OPS
    assert sum(
        s["value"] for s in final["s_by_worker_total"]["samples"]
    ) == N_WRITERS * N_OPS
