"""zstd codec: ctypes-libzstd fast path and the pure-Python RFC 8878
decoder (io/zstd_py.py), cross-checked against each other and fuzzed like
the sibling codecs (librdkafka gives the reference zstd support for free,
/root/reference/Cargo.toml:19)."""

import os
import random
import struct

import pytest

from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io import zstd_py
from kafka_topic_analyzer_tpu.io.compression import (
    _load_libzstd,
    decompress,
    zstd_compress_frame,
    zstd_decompress,
)

CASES = [
    b"",
    b"a",
    b"hello world " * 50,
    bytes(1000),                                   # RLE-friendly
]


def _corpus():
    rng = random.Random(7)
    out = list(CASES)
    out.append(os.urandom(1000))                   # incompressible
    out.append(bytes(rng.choices(b"abcdefgh", k=5000)))   # Huffman-friendly
    out.append((b"key-%d value payload " * 200) % tuple(range(200)))
    out.append(os.urandom(300_000))                # multi-block
    out.append(bytes(rng.choices(range(256), k=200_000)))
    return out


@pytest.mark.parametrize("level", [1, 3, 19])
def test_python_decoder_matches_libzstd(level):
    if _load_libzstd() is None:
        pytest.skip("libzstd unavailable: nothing to cross-check against")
    for data in _corpus():
        comp = zstd_compress_frame(data, level)
        assert zstd_decompress(comp) == data           # ctypes path
        assert zstd_py.decompress(comp, 1 << 30) == data  # pure Python


def test_literal_frame_fallback_roundtrip():
    """The literal-only encoder (used when libzstd is absent) emits valid
    frames both decoders accept — including multi-block (>128 KiB)."""
    for data in (b"", b"abc", os.urandom(300_000)):
        import kafka_topic_analyzer_tpu.io.compression as comp_mod

        saved = comp_mod._libzstd
        comp_mod._libzstd = None  # force the literal encoder
        try:
            frame = zstd_compress_frame(data)
        finally:
            comp_mod._libzstd = saved
        assert zstd_py.decompress(frame, 1 << 30) == data
        assert zstd_decompress(frame) == data


def _stream_compress_chunked(data: bytes, chunk: int = 1000) -> bytes:
    """ZSTD_compressStream2 fed in chunks so the frame header carries NO
    content size — the shape real stream-compressing Kafka producers emit
    (the one-shot ZSTD_compress always pledges the size)."""
    import ctypes

    lib = _load_libzstd()
    lib.ZSTD_createCCtx.restype = ctypes.c_void_p
    lib.ZSTD_compressStream2.restype = ctypes.c_size_t

    class Buf(ctypes.Structure):
        _fields_ = [
            ("ptr", ctypes.c_void_p),
            ("size", ctypes.c_size_t),
            ("pos", ctypes.c_size_t),
        ]

    cctx = lib.ZSTD_createCCtx()
    cap = int(lib.ZSTD_compressBound(len(data))) + 1024
    dst = ctypes.create_string_buffer(cap)
    outbuf = Buf(ctypes.cast(dst, ctypes.c_void_p), cap, 0)
    pos = 0
    while True:
        piece = data[pos : pos + chunk]
        pos += len(piece)
        last = pos >= len(data)
        src = ctypes.create_string_buffer(piece, len(piece))
        inbuf = Buf(ctypes.cast(src, ctypes.c_void_p), len(piece), 0)
        while True:
            ret = int(lib.ZSTD_compressStream2(
                ctypes.c_void_p(cctx), ctypes.byref(outbuf),
                ctypes.byref(inbuf), 2 if last else 0,
            ))
            assert not lib.ZSTD_isError(ret)
            if inbuf.pos >= inbuf.size and (not last or ret == 0):
                break
        if last:
            break
    lib.ZSTD_freeCCtx(ctypes.c_void_p(cctx))
    return dst.raw[: outbuf.pos]


def test_streamed_frames_without_content_size():
    """The production-common frame shape: no declared content size, decoded
    via ZSTD_decompressStream (and the pure-Python block loop)."""
    if _load_libzstd() is None:
        pytest.skip("libzstd unavailable")
    rng = random.Random(3)
    for data in (
        b"hello world " * 500,
        os.urandom(100_000),
        bytes(rng.choices(b"abcdef", k=300_000)),
    ):
        comp = _stream_compress_chunked(data)
        lib = _load_libzstd()
        fcs = int(lib.ZSTD_getFrameContentSize(comp, len(comp)))
        assert fcs == (1 << 64) - 1  # CONTENTSIZE_UNKNOWN
        assert zstd_decompress(comp) == data
        assert zstd_py.decompress(comp, 1 << 30) == data


def test_streamed_frame_exact_chunk_fill():
    """A streamed frame whose output exactly fills the decode chunk buffer
    must complete on the fast path (regression: the loop once required a
    non-full final chunk and demoted these to the pure-Python decoder)."""
    if _load_libzstd() is None:
        pytest.skip("libzstd unavailable")
    from kafka_topic_analyzer_tpu.io.compression import _zstd_stream_decompress

    data = b"A" * (256 * 1024)  # compresses tiny -> chunk_size = 256 KiB
    comp = _stream_compress_chunked(data)
    assert _zstd_stream_decompress(_load_libzstd(), comp) == data


def test_match_offset_cannot_cross_frame_boundary():
    """Frames are independent: a match in frame 2 reaching into frame 1's
    output is corrupt (libzstd rejects it; so must the Python decoder).
    Frame 2 is hand-built with RLE sequence tables: literals 'DEF' then one
    sequence (ll=3, offset=5, ml=4) — offset 5 exceeds the 3 bytes this
    frame has produced."""
    f1 = zstd_compress_frame(b"ABCDEFGH", 1)
    block = b"\x18DEF" + bytes([0x01, 0x54, 0x03, 0x03, 0x01, 0x08])
    h = 1 | (2 << 1) | (len(block) << 3)
    f2 = (
        struct.pack("<IB", zstd_py.ZSTD_MAGIC, 0x20)
        + b"\x07"  # declared content size 7
        + struct.pack("<I", h)[:3]
        + block
    )
    with pytest.raises(ValueError, match="frame start"):
        zstd_py.decompress(f2, 1 << 20)  # invalid even standalone
    with pytest.raises(ValueError, match="frame start"):
        zstd_py.decompress(f1 + f2, 1 << 20)


def test_multi_frame_and_skippable():
    a = zstd_compress_frame(b"first frame ", 3)
    skip = struct.pack("<II", 0x184D2A53, 5) + b"xxxxx"
    b = zstd_compress_frame(b"second", 19)
    assert zstd_py.decompress(a + skip + b, 1 << 30) == b"first frame second"


def test_python_decoder_respects_cap():
    comp = zstd_compress_frame(b"x" * 50_000, 3)
    with pytest.raises(ValueError, match="cap"):
        zstd_py.decompress(comp, 1000)


def test_dictionary_frames_rejected():
    # Single-segment frame with a nonzero 1-byte dictionary id.
    frame = struct.pack("<IB", zstd_py.ZSTD_MAGIC, 0x21) + b"\x07" + b"\x00" * 8
    with pytest.raises(ValueError, match="dictionar"):
        zstd_py.decompress(frame, 1 << 20)


def test_fuzz_garbage_and_truncations_total():
    """Decoder totality: arbitrary garbage, truncations, and bit flips must
    raise ValueError or return bytes — never crash, hang, or leak another
    exception type (same contract as the snappy/LZ4 fuzz suites)."""
    rng = random.Random(11)
    base = zstd_compress_frame(bytes(rng.choices(b"abcdef", k=3000)), 19)
    for i in range(200):
        buf = bytearray(base)
        mode = i % 3
        if mode == 0:
            buf = bytearray(rng.randbytes(rng.randrange(1, 200)))
        elif mode == 1:
            buf = buf[: rng.randrange(1, len(buf))]
        else:
            for _ in range(rng.randrange(1, 6)):
                buf[rng.randrange(len(buf))] ^= rng.randrange(1, 256)
        try:
            zstd_py.decompress(bytes(buf), 1 << 20)
        except ValueError:
            pass


def test_record_batch_roundtrip_zstd():
    records = [
        (10, 1_600_000_000_000, b"key-a", b"value-a" * 10),
        (11, 1_600_000_000_001, None, b"v"),
        (12, 1_600_000_000_002, b"key-b", None),
    ]
    buf = kc.encode_record_batch(records, kc.COMPRESSION_ZSTD)
    got = [
        (off, ts, k, v)
        for off, (ts, k, v) in kc.decode_record_batches(buf, verify_crc=True)
    ]
    assert got == records


def test_codec_dispatch():
    assert decompress(4, zstd_compress_frame(b"payload")) == b"payload"
