"""The remote segment tier (DESIGN.md §21): object-store scans must be
byte-identical to local-directory scans of the same chunks — across
workers × superbatch × readahead, under injected transport faults, through
the local segment cache, and across cross-store resume — with the PR-1
degraded surface and the PR-3 corruption taxonomy carried over intact.
"""

import json
import os

import numpy as np
import pytest
from fake_objstore import FakeObjectStore

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    SegmentFetchConfig,
    TransportRetryConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.segfile import (
    MalformedSegmentError,
    SegmentFileSource,
    write_segment_from_batches,
)
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.obs.registry import default_registry

pytestmark = pytest.mark.objstore

SPEC = SyntheticSpec(
    num_partitions=3,
    messages_per_partition=2_000,
    keys_per_partition=90,
    tombstone_permille=130,
    seed=11,
)
#: Fast-failing retry schedule for fault tests (no real sleeping to speak
#: of; the budget semantics are what is under test).
FAST_RETRY = TransportRetryConfig(
    backoff_ms=1, backoff_max_ms=4, retry_budget=4
)


def fetch_cfg(readahead=2, cache=None, retry=FAST_RETRY, timeout=5.0):
    return SegmentFetchConfig(
        readahead=readahead, cache_dir=cache, retry=retry, timeout_s=timeout
    )


@pytest.fixture()
def seg_dir(tmp_path):
    src = SyntheticSource(SPEC)
    d = tmp_path / "segs"
    d.mkdir()
    for p in src.partitions():
        write_segment_from_batches(
            str(d), "t", p, list(src.batches(700, partitions=[p]))
        )
    return str(d)


def cpu_cfg(**kw):
    base = dict(
        num_partitions=3, batch_size=700, count_alive_keys=True,
        alive_bitmap_bits=18, enable_hll=True, hll_p=8,
    )
    base.update(kw)
    return AnalyzerConfig(**base)


def scan_doc(result):
    d = result.metrics.to_dict(result.start_offsets, result.end_offsets)
    d["degraded"] = dict(result.degraded_partitions)
    return d


def metric_total(name):
    m = default_registry().snapshot().get(name)
    if not m:
        return 0.0
    return sum(s["value"] for s in m["samples"])


# ---------------------------------------------------------------------------
# store factory / spec parsing


def test_open_segment_store_routes_remote_schemes(seg_dir, monkeypatch):
    from kafka_topic_analyzer_tpu.io.objstore import parse_object_store_spec
    from kafka_topic_analyzer_tpu.io.segstore import (
        ObjectSegmentStore,
        open_segment_store,
    )

    assert isinstance(
        open_segment_store("http://127.0.0.1:9/bucket"), ObjectSegmentStore
    )
    assert isinstance(
        open_segment_store("https://s3.example.com/bucket/p"),
        ObjectSegmentStore,
    )
    assert isinstance(open_segment_store("s3://bucket/pre"), ObjectSegmentStore)
    # s3:// resolves through KTA_S3_ENDPOINT, path-style.
    monkeypatch.setenv("KTA_S3_ENDPOINT", "http://minio.local:9000")
    assert parse_object_store_spec("s3://arch/orders") == (
        False, "minio.local", 9000, "/arch/orders"
    )
    assert parse_object_store_spec("http://h:81/b") == (False, "h", 81, "/b")
    assert parse_object_store_spec("https://h/b")[:3] == (True, "h", 443)
    with pytest.raises(ValueError, match="bad object store spec"):
        parse_object_store_spec("ftp://nope")


def test_unknown_scheme_lists_supported(tmp_path):
    from kafka_topic_analyzer_tpu.io.segstore import open_segment_store

    with pytest.raises(ValueError, match="not supported") as e:
        open_segment_store("gs://bucket/prefix")
    for spelled in ("file://", "http://", "https://", "s3://", "plug-in"):
        assert spelled in str(e.value)


def test_cache_rejected_for_local_store(seg_dir, tmp_path):
    from kafka_topic_analyzer_tpu.io.segstore import open_segment_store

    with pytest.raises(ValueError, match="--segment-cache only applies"):
        open_segment_store(
            seg_dir, fetch=fetch_cfg(cache=str(tmp_path / "c"))
        )


# ---------------------------------------------------------------------------
# catalog over the wire


def test_remote_catalog_uses_header_probes_only(seg_dir):
    with FakeObjectStore(seg_dir) as store:
        src = SegmentFileSource(store.url, "t", fetch=fetch_cfg())
        # Validation complete (header↔name, ordering, sizes) with ZERO
        # chunk bodies downloaded.
        assert sum(store.body_gets.values()) == 0
        local = SegmentFileSource(seg_dir, "t")
        assert src.partitions() == local.partitions()
        assert src.watermarks() == local.watermarks()
        assert src.partition_record_counts() == local.partition_record_counts()
        assert src.readahead == 2  # the explicit fetch_cfg depth


def test_remote_catalog_auto_readahead_and_gappy_end_offsets(tmp_path):
    from kafka_topic_analyzer_tpu.io.kafka_wire import records_to_batch
    from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter
    from kafka_topic_analyzer_tpu.records import RecordBatch

    rows = [
        (0, 1_600_000_000_000 + off, f"k{off % 7}".encode(), bytes(12))
        for off in range(0, 300, 3)
    ]
    batch = records_to_batch(rows)
    batch.offsets = np.arange(0, 300, 3, dtype=np.int64)
    writer = SegmentDumpWriter(str(tmp_path), "gap", records_per_chunk=40)
    for lo in range(0, 100, 25):
        writer.append(batch.take(np.arange(lo, lo + 25)))
    writer.close()

    with FakeObjectStore(str(tmp_path)) as store:
        src = SegmentFileSource(store.url, "gap")  # default fetch config
        assert src.readahead == 4  # auto resolves to 4 for remote stores
        # Offset-exact watermarks from the 8-byte suffix probes — still no
        # body fetches.
        assert src.watermarks() == (({0: 0}), ({0: 298}))
        assert sum(store.body_gets.values()) == 0
        # Offset-exact resume mid-chunk (this one does read bodies).
        resumed = RecordBatch.concat(list(src.batches(50, start_at={0: 151})))
        assert int(resumed.offsets[0]) == 153


def _write_gappy_chunks(tmp_path):
    from kafka_topic_analyzer_tpu.io.kafka_wire import records_to_batch
    from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter

    rows = [
        (0, 1_600_000_000_000 + off, f"k{off % 7}".encode(), bytes(12))
        for off in range(0, 300, 3)
    ]
    batch = records_to_batch(rows)
    batch.offsets = np.arange(0, 300, 3, dtype=np.int64)
    writer = SegmentDumpWriter(str(tmp_path), "gap", records_per_chunk=40)
    for lo in range(0, 100, 25):
        writer.append(batch.take(np.arange(lo, lo + 25)))
    writer.close()


def test_resume_plan_probes_only_the_straddling_chunk(tmp_path):
    """Resuming mid-archive must touch exactly ONE chunk's offsets column
    at plan time (the chunk straddling the resume point): probing every
    remaining gappy chunk would synchronously download the rest of the
    archive up front and pin it all in memory."""
    _write_gappy_chunks(tmp_path)  # c0 = offsets 0..147, c1 = 150..297
    with FakeObjectStore(str(tmp_path)) as store:
        src = SegmentFileSource(store.url, "gap", fetch=fetch_cfg(0))
        it = src.batches(50, start_at={0: 100})
        got = next(it)
        assert int(got.offsets[0]) == 102
        it.close()
        # c0 straddles 100 and is probed; c1 is entirely above the resume
        # point and must not be fetched at plan time.
        assert store.body_gets["gap-0.c0.ktaseg"] == 1
        assert store.body_gets["gap-0.c1.ktaseg"] == 0


def test_resume_plan_probe_failure_degrades_not_crashes(tmp_path):
    """A plan-time offsets probe that exhausts the partition's transport
    budget degrades that partition (the PR-1 surface) — it must not
    escape batches() and crash the resumed scan."""
    _write_gappy_chunks(tmp_path)
    with FakeObjectStore(str(tmp_path)) as store:
        store.script("gap-0.c0.ktaseg", *[("status", 503)] * 32)
        src = SegmentFileSource(store.url, "gap", fetch=fetch_cfg(0))
        assert list(src.batches(50, start_at={0: 100})) == []
        assert list(src.degraded_partitions()) == [0]
        assert "failures" in src.degraded_partitions()[0]


def test_prefixed_store_spec_lists_and_fetches(seg_dir):
    """A /bucket/some/prefix spec must LIST against the BUCKET with the
    key prefix folded into ?prefix=, and GET prefixed keys — a prefixed
    archive layout scans byte-identically to the flat one."""
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    objects = {
        f"arch/2026/{name}": data
        for name, data in _as_dict_root(seg_dir).items()
    }
    with FakeObjectStore(objects, bucket="tiered") as store:
        spec = f"http://127.0.0.1:{store.port}/tiered/arch/2026"
        src = SegmentFileSource(spec, "t", fetch=fetch_cfg(2))
        assert src.partitions() == [0, 1, 2]
        got = run_scan(
            "t", src, CpuExactBackend(cfg, init_now_s=10**10), 700
        )
        assert scan_doc(got) == ref
        assert store.body_gets["arch/2026/t-0.ktaseg"] == 1


# ---------------------------------------------------------------------------
# the acceptance matrix: remote == local across workers × K × readahead


def test_remote_scan_matches_local_matrix(seg_dir):
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import DispatchConfig

    cfg = cpu_cfg(batch_size=256, enable_quantiles=True)
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        TpuBackend(cfg, init_now_s=10**10), 256,
    ))
    with FakeObjectStore(seg_dir) as store:
        for workers in (1, 4):
            for k in (1, 4):
                for readahead in (0, 2):
                    backend = TpuBackend(
                        cfg, init_now_s=10**10,
                        dispatch=DispatchConfig(superbatch=k),
                    )
                    got = run_scan(
                        "t",
                        SegmentFileSource(
                            store.url, "t", fetch=fetch_cfg(readahead)
                        ),
                        backend, 256, ingest_workers=workers,
                    )
                    assert got.superbatch_k == k
                    assert got.ingest_workers == min(workers, 3)
                    assert scan_doc(got) == ref, (workers, k, readahead)
    # Every per-stream read-ahead pool drained and settled: the occupancy
    # gauge must be back at zero.
    assert metric_total("kta_segstore_readahead_occupancy") == 0


# ---------------------------------------------------------------------------
# fault injection: transient → retried, persistent → degraded


def test_mid_get_faults_are_retried_to_identity(seg_dir):
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    retries0 = metric_total("kta_segstore_retries_total")
    with FakeObjectStore(seg_dir) as store:
        # One mid-GET connection drop, one 5xx, one stall past the client
        # timeout — three distinct transient kinds on three chunks.
        store.script("t-0.ktaseg", "drop")
        store.script("t-1.ktaseg", ("status", 503))
        store.script("t-2.ktaseg", ("stall", 1.0))
        got = run_scan(
            "t",
            SegmentFileSource(
                store.url, "t", fetch=fetch_cfg(readahead=2, timeout=0.4)
            ),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
    assert scan_doc(got) == ref
    assert got.degraded_partitions == {}
    assert metric_total("kta_segstore_retries_total") - retries0 >= 3


def test_truncated_mid_get_is_transient_not_corrupt(seg_dir):
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    with FakeObjectStore(seg_dir) as store:
        # Body cut short mid-GET (headers claim full length): must retry,
        # not classify — the object at rest is intact.
        store.script("t-0.ktaseg", ("truncate", 500))
        got = run_scan(
            "t",
            SegmentFileSource(store.url, "t", fetch=fetch_cfg(0)),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
    assert scan_doc(got) == ref


def test_retry_budget_exhaustion_degrades_partition(seg_dir):
    cfg = cpu_cfg()
    with FakeObjectStore(seg_dir) as store:
        store.script("t-1.ktaseg", *[("status", 500)] * 32)
        got = run_scan(
            "t",
            SegmentFileSource(store.url, "t", fetch=fetch_cfg(2)),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
    # Partition 1 degraded with the budget reason; the others finished.
    assert list(got.degraded_partitions) == [1]
    assert "consecutive transport failures" in got.degraded_partitions[1]
    assert got.metrics.overall_count == 2 * SPEC.messages_per_partition
    assert got.metrics.total(0) == SPEC.messages_per_partition
    assert got.metrics.total(1) == 0
    # The engine persists the degraded surface identically to a dead wire
    # partition: the scan result exposes it for EXIT_DEGRADED.
    assert metric_total("kta_retry_budget_exhaustions_total") >= 1


def test_list_pagination_enumerates_full_catalog(seg_dir):
    """S3 caps a LIST page at 1000 keys: the client must follow
    NextContinuationToken until IsTruncated clears, or an archive larger
    than one page silently loses its lexicographic tail."""
    from kafka_topic_analyzer_tpu.io.objstore import RetryingHttp

    def list_gets():
        snap = default_registry().snapshot().get("kta_segstore_gets_total")
        return sum(
            s["value"] for s in (snap or {"samples": []})["samples"]
            if s["labels"].get("kind") == "list"
        )

    objects = {f"t-{i}.ktaseg": b"x" * 8 for i in range(25)}
    with FakeObjectStore(objects, max_keys=10) as store:
        http = RetryingHttp(store.url, fetch_cfg())
        lists0 = list_gets()
        names = sorted(n for n, _ in http.list_objects("t-"))
        assert names == sorted(objects)  # all 25 …
        assert list_gets() - lists0 == 3  # … across 3 pages
    # And end-to-end: a scan against a paginating store (3 chunks, 2-key
    # pages) stays byte-identical to the local reference.
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    with FakeObjectStore(seg_dir, max_keys=2) as store:
        got = run_scan(
            "t",
            SegmentFileSource(store.url, "t", fetch=fetch_cfg(0)),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
    assert scan_doc(got) == ref


def test_sse_kms_etag_is_not_treated_as_damage(seg_dir):
    """SSE-KMS objects carry 32-hex ETags that are NOT the content MD5.
    The response declares the encryption, so the MD5 check must be
    skipped outright — a healthy encrypted archive must not burn retry
    budget (let alone degrade) on 'body MD5 does not match ETag'."""
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    retries0 = metric_total("kta_segstore_retries_total")
    with FakeObjectStore(seg_dir, sse="aws:kms", etag_salt=b"kms") as store:
        got = run_scan(
            "t",
            SegmentFileSource(store.url, "t", fetch=fetch_cfg(0)),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        # No re-fetches at all: every chunk body downloaded exactly once.
        assert all(v == 1 for v in store.body_gets.values())
    assert scan_doc(got) == ref
    assert got.degraded_partitions == {}
    assert metric_total("kta_segstore_retries_total") - retries0 == 0


def test_persistent_etag_mismatch_accepted_after_one_refetch(seg_dir):
    """A 32-hex non-MD5 ETag WITHOUT the SSE header (proxy-stripped
    headers, composite ETags): the first mismatch is presumed in-flight
    damage and re-fetched once; byte-identical data on the second fetch
    proves it persistent — accepted, booked, and LATCHED for the whole
    store (ETag policy is bucket-level), so an archived year pays one
    extra fetch total, not 2x egress."""
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    booked0 = metric_total("kta_segstore_fallback_total")
    with FakeObjectStore(seg_dir, etag_salt=b"not-md5") as store:
        got = run_scan(
            "t",
            SegmentFileSource(store.url, "t", fetch=fetch_cfg(0)),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        # ONE chunk pays the disambiguating re-fetch; the latch spares
        # the rest of the store.
        assert sum(store.body_gets.values()) == 4
        assert sorted(store.body_gets.values()) == [1, 1, 2]
    assert scan_doc(got) == ref
    assert got.degraded_partitions == {}
    assert metric_total("kta_segstore_fallback_total") - booked0 == 1
    snap = default_registry().snapshot()["kta_segstore_fallback_total"]
    assert any(
        s["labels"].get("reason") == "etag-not-md5" and s["value"] >= 1
        for s in snap["samples"]
    )


def test_range_ignoring_server_is_sliced_not_retried(seg_dir):
    """An endpoint that answers ranged GETs with 200 + the full object:
    the requested window is sliced out client-side (booked) — the
    catalog's header probes must not burn the retry budget calling the
    full body 'truncated'."""
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    retries0 = metric_total("kta_segstore_retries_total")
    with FakeObjectStore(seg_dir, ignore_range=True) as store:
        got = run_scan(
            "t",
            SegmentFileSource(store.url, "t", fetch=fetch_cfg(0)),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
    assert scan_doc(got) == ref
    assert got.degraded_partitions == {}
    assert metric_total("kta_segstore_retries_total") - retries0 == 0
    snap = default_registry().snapshot()["kta_segstore_fallback_total"]
    # >= 1, not one-per-chunk: range-ignoring LATCHES on first detection,
    # so concurrent catalog opens may already ride the whole-object path.
    assert any(
        s["labels"].get("reason") == "range-ignored" and s["value"] >= 1
        for s in snap["samples"]
    )


def test_range_ignoring_store_latches_one_get_per_open(tmp_path):
    """Once a server is known to ignore Range headers, each catalog open
    costs ONE whole-object GET with the header/tail probes sliced locally
    — not a full download per probe (3x the archive over a catalog)."""
    from kafka_topic_analyzer_tpu.io.segstore import ObjectSegmentStore

    _write_gappy_chunks(tmp_path)
    with FakeObjectStore(str(tmp_path), ignore_range=True) as store:
        seg_store = ObjectSegmentStore(
            store.url, fetch=fetch_cfg(0, cache=str(tmp_path / "cache"))
        )
        refs = seg_store.list_refs("gap")
        seg_store.open(refs[0])  # detects + latches mid-open
        assert seg_store.transport.range_ignored
        before = store.requests_served
        f1 = seg_store.open(refs[1])
        assert store.requests_served - before == 1
        assert f1.end_offset == 298  # locally-sliced tail, offset-exact
        # The whole-object probe SEEDED the cache: materializing the body
        # costs no additional GET — one wire crossing per chunk per scan.
        f1.ensure_body()
        assert store.requests_served - before == 1


def test_bucketless_spec_rejected():
    from kafka_topic_analyzer_tpu.io.segstore import open_segment_store

    for spec in ("http://127.0.0.1:9000", "https://host/", "http://h:80//"):
        with pytest.raises(ValueError, match="no bucket"):
            open_segment_store(spec)


# ---------------------------------------------------------------------------
# corrupted fetches: classification + one-re-fetch disambiguation


def _as_dict_root(seg_dir):
    return {
        f: open(os.path.join(seg_dir, f), "rb").read()
        for f in os.listdir(seg_dir)
    }


def test_at_rest_corruption_classifies_after_one_refetch(seg_dir):
    objects = _as_dict_root(seg_dir)
    with FakeObjectStore(objects) as store:
        src = SegmentFileSource(store.url, "t", fetch=fetch_cfg(0))
        # Corrupt the OBJECT after the catalog validated its header: every
        # fetch now returns the same damaged bytes (ETag matches them, so
        # the MD5 check cannot save us — this is at-rest damage).
        data = bytearray(objects["t-1.ktaseg"])
        data[9] ^= 0xFF  # inside the header's partition field
        objects["t-1.ktaseg"] = bytes(data)
        refetches0 = metric_total("kta_corrupt_refetches_total")
        with pytest.raises(MalformedSegmentError) as e:
            for _ in src.batches(700):
                pass
        # Classified with the local reader's taxonomy + path context, and
        # the disambiguating re-fetch happened exactly once.
        assert e.value.kind == "malformed-header"
        assert "t-1.ktaseg" in str(e.value)
        assert metric_total("kta_corrupt_refetches_total") - refetches0 == 1
        assert store.body_gets["t-1.ktaseg"] == 2  # fetch + one re-fetch


def test_in_flight_corruption_is_healed_by_refetch(seg_dir):
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    # (a) With ETags suppressed, a one-shot bit flip inside the header
    # region fails classification, and the ONE structural re-fetch heals
    # it — byte-identical scan, no corruption surfaced.
    with FakeObjectStore(seg_dir, send_etag=False) as store:
        store.script("t-0.ktaseg", ("flip", 9))
        got = run_scan(
            "t",
            SegmentFileSource(store.url, "t", fetch=fetch_cfg(0)),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        assert scan_doc(got) == ref
        assert store.body_gets["t-0.ktaseg"] == 2
    # (b) With ETags on, the SAME flip anywhere in the body is caught by
    # the MD5 integrity check before classification ever runs, and
    # retried as a transient.
    retries0 = metric_total("kta_segstore_retries_total")
    with FakeObjectStore(seg_dir) as store:
        store.script("t-0.ktaseg", ("flip", 5000))
        got = run_scan(
            "t",
            SegmentFileSource(store.url, "t", fetch=fetch_cfg(0)),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        assert scan_doc(got) == ref
    assert metric_total("kta_segstore_retries_total") - retries0 >= 1


# ---------------------------------------------------------------------------
# the local segment cache


def test_cache_cold_fills_warm_serves_byte_identical(seg_dir, tmp_path):
    cfg = cpu_cfg()
    cache = str(tmp_path / "cache")
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    hits0 = metric_total("kta_segstore_cache_hits_total")
    misses0 = metric_total("kta_segstore_cache_misses_total")
    with FakeObjectStore(seg_dir) as store:
        for expect_body_gets in (3, 0):  # cold fetches all 3; warm none
            before = sum(store.body_gets.values())
            got = run_scan(
                "t",
                SegmentFileSource(
                    store.url, "t", fetch=fetch_cfg(2, cache=cache)
                ),
                CpuExactBackend(cfg, init_now_s=10**10), 700,
            )
            assert scan_doc(got) == ref
            assert (
                sum(store.body_gets.values()) - before == expect_body_gets
            )
    assert metric_total("kta_segstore_cache_misses_total") - misses0 == 3
    assert metric_total("kta_segstore_cache_hits_total") - hits0 == 3


def test_poisoned_cache_entry_refetched_never_served(seg_dir, tmp_path):
    cfg = cpu_cfg()
    cache = str(tmp_path / "cache")
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    with FakeObjectStore(seg_dir) as store:
        fetch = fetch_cfg(2, cache=cache)
        run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        # Flip one byte inside a cached entry (bit rot at rest in the
        # cache itself — NOT in the store).
        entry = sorted(
            f for f in os.listdir(cache) if f.endswith(".seg")
        )[0]
        path = os.path.join(cache, entry)
        data = bytearray(open(path, "rb").read())
        data[4321] ^= 0x10
        open(path, "wb").write(bytes(data))
        before = sum(store.body_gets.values())
        poisoned0 = metric_total("kta_segstore_fallback_total")
        got = run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        # Detected, booked, re-fetched — and the results never saw the
        # flipped bytes.
        assert scan_doc(got) == ref
        assert sum(store.body_gets.values()) - before == 1
        assert metric_total("kta_segstore_fallback_total") - poisoned0 == 1
        snap = default_registry().snapshot()["kta_segstore_fallback_total"]
        assert any(
            s["labels"].get("reason") == "cache-poisoned" and s["value"] >= 1
            for s in snap["samples"]
        )


def test_stale_cache_entry_is_miss_not_corruption(seg_dir, tmp_path):
    """An entry that matches its OWN sha256 sidecar but no longer matches
    the catalog's header (the archive was re-dumped at the same name and
    size) must be evicted and re-fetched — never classified as fatal
    corruption."""
    import struct

    cfg = cpu_cfg()
    cache = str(tmp_path / "cache")
    objects = _as_dict_root(seg_dir)
    with FakeObjectStore(objects) as store:
        fetch = fetch_cfg(0, cache=cache)
        run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        # Re-dump the archive: same names/sizes, start offsets shifted
        # (the header changes, the sidecar-verified cache entries do not).
        for name in list(objects):
            data = bytearray(objects[name])
            data[16:24] = struct.pack("<q", 500)  # start_offset
            objects[name] = bytes(data)
        stale0 = metric_total("kta_segstore_fallback_total")
        got = run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        # The NEW dump's offsets — fresh bytes, not the stale entries.
        assert got.start_offsets == {0: 500, 1: 500, 2: 500}
        assert got.degraded_partitions == {}
        assert metric_total("kta_segstore_fallback_total") - stale0 == 3
        snap = default_registry().snapshot()["kta_segstore_fallback_total"]
        assert any(
            s["labels"].get("reason") == "cache-stale" and s["value"] >= 3
            for s in snap["samples"]
        )
        # And the re-dump is now cached: a third scan hits, no body GETs.
        before = sum(store.body_gets.values())
        run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        assert sum(store.body_gets.values()) == before


def test_cache_reinsert_does_not_double_count(tmp_path):
    """Re-inserting an existing digest replaces its bytes: the running
    resident-bytes estimate must grow by the NET change only, or racing
    fetches of one chunk inflate it and trigger premature full-directory
    eviction sweeps."""
    from kafka_topic_analyzer_tpu.io.objstore import SegmentCache

    cache = SegmentCache(str(tmp_path / "c"), 100, "store")
    cache.put("a", 60, b"x" * 60)
    cache.put("a", 60, b"x" * 60)
    assert cache._total == 60
    # A second distinct entry fits the bound exactly — no sweep runs.
    evict0 = metric_total("kta_segstore_cache_evictions_total")
    cache.put("b", 30, b"y" * 30)
    assert cache._total == 90
    assert metric_total("kta_segstore_cache_evictions_total") == evict0
    resident = [
        f for f in os.listdir(str(tmp_path / "c")) if f.endswith(".seg")
    ]
    assert len(resident) == 2


def test_cache_lru_eviction_bounds_directory(seg_dir, tmp_path):
    cfg = cpu_cfg()
    cache = str(tmp_path / "cache")
    sizes = {
        f: os.path.getsize(os.path.join(seg_dir, f))
        for f in os.listdir(seg_dir)
    }
    # Bound below two chunks: after every insert the LRU sweep keeps the
    # newest entry and evicts back under the bound.
    bound = max(sizes.values()) + 10
    evict0 = metric_total("kta_segstore_cache_evictions_total")
    with FakeObjectStore(seg_dir) as store:
        fetch = SegmentFetchConfig(
            readahead=0, cache_dir=cache, cache_max_bytes=bound,
            retry=FAST_RETRY, timeout_s=5,
        )
        run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
    resident = sum(
        os.path.getsize(os.path.join(cache, f))
        for f in os.listdir(cache) if f.endswith(".seg")
    )
    assert resident <= bound
    assert metric_total("kta_segstore_cache_evictions_total") - evict0 >= 2


# ---------------------------------------------------------------------------
# cross-store resume


class _Interrupt(Exception):
    pass


class _InterruptingSegSource(SegmentFileSource):
    """Raises after yielding `limit` batches on the initial pass (resume
    passes — start_at set — run to completion)."""

    def __init__(self, *a, limit=2, **kw):
        super().__init__(*a, **kw)
        self.limit = limit

    def batches(self, batch_size, partitions=None, start_at=None, sink=None):
        it = super().batches(batch_size, partitions, start_at, sink=sink)
        for i, b in enumerate(it):
            if start_at is None and i >= self.limit:
                raise _Interrupt()
            yield b


def test_cross_store_resume_both_directions(seg_dir, tmp_path):
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend

    cfg = cpu_cfg(batch_size=512)
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        TpuBackend(cfg, init_now_s=77), 512,
    ))
    with FakeObjectStore(seg_dir) as store:
        def remote_src(interrupting=False, **kw):
            cls = _InterruptingSegSource if interrupting else SegmentFileSource
            return cls(store.url, "t", fetch=fetch_cfg(2), **kw)

        # local snapshot → remote completion
        snap1 = str(tmp_path / "snap1")
        with pytest.raises(_Interrupt):
            run_scan(
                "t",
                _InterruptingSegSource(seg_dir, "t", limit=2),
                TpuBackend(cfg, init_now_s=77), 512,
                snapshot_dir=snap1, snapshot_every_s=0.0,
            )
        got = run_scan(
            "t", remote_src(), TpuBackend(cfg, init_now_s=0), 512,
            snapshot_dir=snap1, resume=True,
        )
        assert scan_doc(got) == ref

        # remote snapshot → local completion
        snap2 = str(tmp_path / "snap2")
        with pytest.raises(_Interrupt):
            run_scan(
                "t", remote_src(interrupting=True, limit=2),
                TpuBackend(cfg, init_now_s=77), 512,
                snapshot_dir=snap2, snapshot_every_s=0.0,
            )
        got = run_scan(
            "t", SegmentFileSource(seg_dir, "t"),
            TpuBackend(cfg, init_now_s=0), 512,
            snapshot_dir=snap2, resume=True,
        )
        assert scan_doc(got) == ref


# ---------------------------------------------------------------------------
# CLI e2e + unsupported-combination errors


def test_cli_remote_scan_json_with_cache_and_digest(seg_dir, tmp_path, capsys):
    from kafka_topic_analyzer_tpu.cli import main
    from kafka_topic_analyzer_tpu.results import SegmentStats

    cache = str(tmp_path / "cache")
    before = SegmentStats.from_telemetry(default_registry().snapshot())
    with FakeObjectStore(seg_dir) as store:
        assert main([
            "-t", "t", "--source", "segfile", "--segment-dir", store.url,
            "--segment-readahead", "2", "--segment-cache", cache,
            "--backend", "cpu", "-c", "--alive-bitmap-bits", "18",
            "--batch-size", "700", "--json", "--quiet", "--native", "off",
        ]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["overall"]["count"] == 3 * SPEC.messages_per_partition
        seg = doc["segments"]
        # The remote-tier block rides the segments digest (deltas: the
        # registry is cumulative under pytest).
        assert seg["store_gets"] - before.gets >= 4  # list + headers + bodies
        assert seg["store_bytes_fetched"] > before.bytes_fetched
        assert seg["cache_misses"] - before.cache_misses == 3
        assert "kta_segstore_gets_total" in doc["telemetry"]
        assert os.path.isdir(cache)


def test_cli_degraded_remote_scan_exits_3(seg_dir, capsys):
    from kafka_topic_analyzer_tpu.cli import EXIT_DEGRADED, main

    with FakeObjectStore(seg_dir) as store:
        store.script("t-2.ktaseg", *[("status", 503)] * 32)
        # The remote tier honors the wire scan's retry knobs through the
        # same --librdkafka spellings — shrink the schedule so budget
        # exhaustion is fast.
        rc = main([
            "-t", "t", "--source", "segfile", "--segment-dir", store.url,
            "--segment-readahead", "0", "--backend", "cpu",
            "--librdkafka",
            "retry.backoff.ms=1,reconnect.backoff.max.ms=4,"
            "transport.retry.budget=3",
            "--batch-size", "700", "--quiet", "--native", "off",
        ])
    assert rc == EXIT_DEGRADED
    out = capsys.readouterr().out
    assert "DEGRADED" in out


def test_follow_and_fleet_reject_segment_stores(seg_dir, capsys):
    from kafka_topic_analyzer_tpu.cli import main

    rc = main([
        "-t", "t", "--source", "segfile", "--segment-dir", seg_dir,
        "--follow", "--quiet",
    ])
    assert rc == 1
    err = capsys.readouterr().err
    # The rejection names the semantics AND the lifting path.
    assert "immutable" in err and "moving head" in err
    assert "--dump-segments" in err

    rc = main([
        "-t", "t", "--source", "segfile", "--segment-dir", seg_dir,
        "--fleet", "--quiet",
    ])
    assert rc == 1
    err = capsys.readouterr().err
    assert "--fleet requires --source kafka" in err
    assert "scan it solo" in err

    rc = main([
        "-t", "t", "--source", "kafka", "-b", "127.0.0.1:9",
        "--segment-cache", "/tmp/nope", "--quiet",
    ])
    assert rc == 1
    assert "--segment-cache requires --source segfile" in (
        capsys.readouterr().err
    )


def test_segment_dir_error_mentions_remote_specs(capsys):
    from kafka_topic_analyzer_tpu.cli import main

    with pytest.raises(SystemExit) as e:
        main(["-t", "t", "--source", "segfile", "--quiet"])
    msg = str(e.value)
    assert "http(s)://" in msg and "s3://" in msg


# ---------------------------------------------------------------------------
# bench smoke


def test_bench_segments_remote_smoke(capsys):
    from kafka_topic_analyzer_tpu.tools.bench_segments import main as bench

    assert bench([
        "--records", "8000", "--partitions", "2", "--chunk-records", "2000",
        "--workers", "2", "--store", "serve", "--inject-latency-ms", "1",
        "--readahead", "0,2", "--repeat", "1", "--native", "off",
    ]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["store"] == "serve"
    assert set(doc["seg_msgs_per_sec"]) == {"w2.ra0", "w2.ra2"}
    assert all(v > 0 for v in doc["seg_msgs_per_sec"].values())
