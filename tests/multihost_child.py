"""Child process for the turnkey multi-host test (test_multihost.py).

Each process runs the SAME code — the turnkey contract (SURVEY.md §5.8):
initialize jax.distributed, build the global (data, space) mesh, and let
the engine feed exactly the data rows this process hosts
(`ShardedTpuBackend.local_rows`).  Process 0 writes the merged metrics
dict as JSON; the parent test compares it against a single-process run.

Usage: python multihost_child.py <pid> <nprocs> <port> <out.json>
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault("KTA_ACCEL_OK", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kafka_topic_analyzer_tpu.jax_support import force_platform  # noqa: E402

force_platform("cpu")

import jax  # noqa: E402


class _Interrupt(RuntimeError):
    pass


class _StepBomb:
    """Raise after N collective steps — N is the SAME on every process
    (update_shards runs in lockstep), so the interrupt is synchronized
    and no process is left waiting in a collective."""

    def __init__(self, inner, limit: int):
        self._inner = inner
        self._limit = limit
        self._n = 0

    def update_shards(self, batches):
        self._n += 1
        if self._n > self._limit:
            raise _Interrupt()
        return self._inner.update_shards(batches)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def main() -> int:
    pid, nprocs, port, out_path = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    )
    mode = sys.argv[5] if len(sys.argv) > 5 else "plain"
    snap_dir = sys.argv[6] if len(sys.argv) > 6 else None
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )
    assert len(jax.devices()) == 8, jax.devices()
    assert jax.local_device_count() == 4

    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.synthetic import (
        SyntheticSource,
        SyntheticSpec,
    )
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    # The "workers" mode spreads 16 partitions over the 8 data rows (2
    # per row) so a per-controller --ingest-workers 8 budget gives every
    # row a real 2-worker fan-in; the other modes keep the original 6.
    n_partitions = 16 if mode == "workers" else 6
    spec = SyntheticSpec(
        num_partitions=n_partitions,
        messages_per_partition=5000,
        keys_per_partition=500,
        key_null_permille=50,
        tombstone_permille=100,
        seed=42,
    )
    config = AnalyzerConfig(
        num_partitions=n_partitions,
        batch_size=2048,
        count_alive_keys=True,
        alive_bitmap_bits=16,
        enable_hll=True,
        enable_quantiles=True,
        mesh_shape=(8, 1),
    )
    backend = ShardedTpuBackend(config, init_now_s=10**10)
    # The turnkey contract under test: this process feeds only its rows.
    assert len(backend.local_rows) == 4, backend.local_rows

    if mode == "resume":
        # Interrupted scan with per-step per-process snapshots, then a
        # resumed scan with a FRESH backend — the multi-host
        # checkpoint/resume contract (checkpoint._snapshot_path).
        try:
            run_scan(
                "mh-topic",
                SyntheticSource(spec),
                _StepBomb(backend, 1),
                batch_size=2048,
                snapshot_dir=snap_dir,
                snapshot_every_s=0.0,
            )
            raise AssertionError("interrupt did not fire")
        except _Interrupt:
            pass
        assert os.path.exists(
            os.path.join(snap_dir, f"scan_snapshot.p{pid}of{nprocs}.npz")
        ), "per-process snapshot file missing"

        captured: "list" = []

        class CaptureStart:
            def __init__(self, inner):
                self._inner = inner

            def batches(self, batch_size, partitions=None, start_at=None):
                captured.append(start_at)
                return self._inner.batches(batch_size, partitions, start_at)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        backend = ShardedTpuBackend(config, init_now_s=10**10)
        result = run_scan(
            "mh-topic",
            CaptureStart(SyntheticSource(spec)),
            backend,
            batch_size=2048,
            snapshot_dir=snap_dir,
            resume=True,
        )
        # Resume must actually have engaged: the engine fed this process's
        # shard streams from the snapshot's offsets, not from zero.
        assert any(
            s and any(v > 0 for v in s.values()) for s in captured
        ), f"resume did not advance start offsets: {captured}"
    elif mode == "workers":
        # PR-7 tentpole under real multi-controller: each process runs
        # per-row ParallelIngest fan-ins over ITS shard partitions while
        # the collective rounds stay in lockstep.
        result = run_scan(
            "mh-topic", SyntheticSource(spec), backend, batch_size=2048,
            ingest_workers=8,
        )
        assert result.ingest_workers == 8, result.ingest_workers
        assert result.ingest_workers_per_controller == [8, 8], (
            result.ingest_workers_per_controller
        )
        # Controller-prefixed worker labels: the merged registry carries
        # BOTH controllers' fan-in workers as a disjoint union.
        recs = result.telemetry["kta_ingest_worker_records_total"]["samples"]
        labels = sorted(s["labels"]["worker"] for s in recs)
        assert labels == sorted(
            f"c{c}.{w}" for c in range(2) for w in range(8)
        ), labels
        assert all(s["value"] > 0 for s in recs), recs
    else:
        result = run_scan(
            "mh-topic", SyntheticSource(spec), backend, batch_size=2048
        )

    # Cluster-wide telemetry merge: each process's lag/ETA gauges cover
    # only the partitions ITS local rows feed, so the merged view is a
    # disjoint union — every partition appears exactly once, drained to
    # zero (a process must never report full lag for a partition another
    # process scanned).
    lag = result.telemetry["kta_partition_lag"]["samples"]
    parts = sorted(s["labels"]["partition"] for s in lag)
    assert parts == sorted(str(p) for p in range(n_partitions)), parts
    assert all(s["value"] == 0 for s in lag), lag
    if mode in ("plain", "workers"):
        # The merged counter sums both processes' folds to the full topic.
        # (Not asserted under "resume": the interrupted scan's partial
        # counts share this process's registry with the resumed run's.)
        assert (
            result.telemetry["kta_scan_records_total"]["samples"][0]["value"]
            == n_partitions * 5000
        )

    if jax.process_index() == 0:
        doc = result.metrics.to_dict(result.start_offsets, result.end_offsets)
        with open(out_path, "w") as f:
            json.dump(doc, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
