"""Child process for the turnkey multi-host test (test_multihost.py).

Each process runs the SAME code — the turnkey contract (SURVEY.md §5.8):
initialize jax.distributed, build the global (data, space) mesh, and let
the engine feed exactly the data rows this process hosts
(`ShardedTpuBackend.local_rows`).  Process 0 writes the merged metrics
dict as JSON; the parent test compares it against a single-process run.

Usage: python multihost_child.py <pid> <nprocs> <port> <out.json>
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault("KTA_ACCEL_OK", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kafka_topic_analyzer_tpu.jax_support import force_platform  # noqa: E402

force_platform("cpu")

import jax  # noqa: E402


def main() -> int:
    pid, nprocs, port, out_path = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    )
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )
    assert len(jax.devices()) == 8, jax.devices()
    assert jax.local_device_count() == 4

    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.synthetic import (
        SyntheticSource,
        SyntheticSpec,
    )
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    spec = SyntheticSpec(
        num_partitions=6,
        messages_per_partition=5000,
        keys_per_partition=500,
        key_null_permille=50,
        tombstone_permille=100,
        seed=42,
    )
    config = AnalyzerConfig(
        num_partitions=6,
        batch_size=2048,
        count_alive_keys=True,
        alive_bitmap_bits=16,
        enable_hll=True,
        enable_quantiles=True,
        mesh_shape=(8, 1),
    )
    backend = ShardedTpuBackend(config)
    # The turnkey contract under test: this process feeds only its rows.
    assert len(backend.local_rows) == 4, backend.local_rows
    source = SyntheticSource(spec)
    result = run_scan("mh-topic", source, backend, batch_size=2048)

    if jax.process_index() == 0:
        doc = result.metrics.to_dict(result.start_offsets, result.end_offsets)
        with open(out_path, "w") as f:
            json.dump(doc, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
