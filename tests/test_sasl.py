"""SASL/PLAIN authentication against a credential-enforcing fake broker."""

import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_codec import KafkaProtocolError
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

from fake_broker import FakeBroker

ROWS = [(i, 1_600_000_000_000 + i, f"k{i % 7}".encode(), bytes(20))
        for i in range(120)]

CREDS = {"security.protocol": "sasl_plaintext",
         "sasl.username": "scout", "sasl.password": "hunter2"}


def _broker():
    return FakeBroker("s.topic", {0: ROWS}, sasl_plain=("scout", "hunter2"))


def test_sasl_scan_with_good_credentials():
    with _broker() as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", "s.topic", overrides=dict(CREDS)
        )
        cfg = AnalyzerConfig(num_partitions=1, batch_size=64)
        m = run_scan("s.topic", src, CpuExactBackend(cfg, init_now_s=0), 64).metrics
        src.close()
    assert m.overall_count == 120


def test_sasl_bad_credentials_rejected():
    with _broker() as broker:
        bad = dict(CREDS, **{"sasl.password": "wrong"})
        with pytest.raises(KafkaProtocolError, match="authentication failed"):
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", "s.topic", overrides=bad
            )


def test_unauthenticated_client_gets_dropped():
    with _broker() as broker:
        # No SASL config at all: broker drops the first non-SASL request.
        with pytest.raises(KafkaProtocolError, match="closed the connection"):
            KafkaWireSource(f"127.0.0.1:{broker.port}", "s.topic")


def test_sasl_requires_credentials():
    with pytest.raises(ValueError, match="sasl.username"):
        KafkaWireSource(
            "127.0.0.1:1", "x",
            overrides={"security.protocol": "sasl_plaintext"},
        )


def test_sasl_client_against_non_sasl_broker():
    """Mismatch must surface as a clear handshake error, not a crashed
    broker thread masquerading as a dropped connection."""
    with FakeBroker("s.topic", {0: ROWS}) as broker:  # no SASL required
        with pytest.raises(KafkaProtocolError, match="SASL handshake failed"):
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", "s.topic", overrides=dict(CREDS)
            )


def test_unsupported_mechanism():
    with pytest.raises(ValueError, match="sasl.mechanism"):
        KafkaWireSource(
            "127.0.0.1:1", "x",
            overrides={"security.protocol": "sasl_plaintext",
                       "sasl.mechanism": "GSSAPI",
                       "sasl.username": "u", "sasl.password": "p"},
        )


# ---------------------------------------------------------------------------
# SCRAM-SHA-256 / SCRAM-SHA-512 (RFC 5802 over SaslAuthenticate rounds)


def _scram_creds(mech):
    return {"security.protocol": "sasl_plaintext", "sasl.mechanism": mech,
            "sasl.username": "scout", "sasl.password": "hunter2"}


@pytest.mark.parametrize("mech", ["SCRAM-SHA-256", "SCRAM-SHA-512"])
def test_scram_scan_with_good_credentials(mech):
    with FakeBroker(
        "s.topic", {0: ROWS}, sasl_scram=(mech, "scout", "hunter2")
    ) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", "s.topic", overrides=_scram_creds(mech)
        )
        cfg = AnalyzerConfig(num_partitions=1, batch_size=64)
        m = run_scan("s.topic", src, CpuExactBackend(cfg, init_now_s=0), 64).metrics
        src.close()
    assert m.overall_count == 120


@pytest.mark.parametrize("mech", ["SCRAM-SHA-256", "SCRAM-SHA-512"])
def test_scram_bad_password_rejected(mech):
    with FakeBroker(
        "s.topic", {0: ROWS}, sasl_scram=(mech, "scout", "hunter2")
    ) as broker:
        bad = dict(_scram_creds(mech), **{"sasl.password": "wrong"})
        with pytest.raises(KafkaProtocolError, match="authentication failed"):
            KafkaWireSource(f"127.0.0.1:{broker.port}", "s.topic", overrides=bad)


def test_scram_wrong_username_rejected():
    with FakeBroker(
        "s.topic", {0: ROWS}, sasl_scram=("SCRAM-SHA-256", "scout", "hunter2")
    ) as broker:
        bad = dict(_scram_creds("SCRAM-SHA-256"), **{"sasl.username": "other"})
        with pytest.raises(KafkaProtocolError, match="authentication failed"):
            KafkaWireSource(f"127.0.0.1:{broker.port}", "s.topic", overrides=bad)


def test_scram_mechanism_mismatch():
    """Broker offering only SCRAM-SHA-512 must reject a -256 handshake with
    the offered list in the error."""
    with FakeBroker(
        "s.topic", {0: ROWS}, sasl_scram=("SCRAM-SHA-512", "scout", "hunter2")
    ) as broker:
        with pytest.raises(KafkaProtocolError, match="SCRAM-SHA-512"):
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", "s.topic",
                overrides=_scram_creds("SCRAM-SHA-256"),
            )


def test_scram_client_verifies_server_signature():
    """A broker that accepts the proof but returns a wrong server signature
    (spoofed broker that doesn't know the password) must be rejected by the
    CLIENT."""
    from kafka_topic_analyzer_tpu.io import kafka_codec as kc

    client = kc.ScramClient("SCRAM-SHA-256", "scout", "hunter2")
    server = kc.ScramServer("SCRAM-SHA-256", "scout", "hunter2")
    first = client.first_message()
    server_first = server.handle_first(first)
    final = client.final_message(server_first)
    ok, server_final = server.handle_final(final)
    assert ok
    client.verify_server_final(server_final)  # good signature passes
    with pytest.raises(KafkaProtocolError, match="server signature"):
        client.verify_server_final(b"v=" + b"QUJDREVGRw==")


def test_scram_downgrade_and_malformed_server_messages_rejected():
    """MITM defenses: an iteration count below RFC 7677's 4096 floor is a
    downgrade attack; malformed server bytes must raise the protocol error
    (one clean CLI line), not binascii/Unicode tracebacks."""
    from kafka_topic_analyzer_tpu.io import kafka_codec as kc

    client = kc.ScramClient("SCRAM-SHA-256", "u", "p")
    with pytest.raises(KafkaProtocolError, match="iteration count"):
        client.final_message(b"r=%snonce,s=c2FsdA==,i=1" % client.nonce.encode())
    client2 = kc.ScramClient("SCRAM-SHA-256", "u", "p")
    with pytest.raises(KafkaProtocolError, match="non-UTF-8"):
        client2.final_message(b"\xff\xfe\x00")
    client3 = kc.ScramClient("SCRAM-SHA-256", "u", "p")
    server = kc.ScramServer("SCRAM-SHA-256", "u", "p")
    client3.final_message(server.handle_first(client3.first_message()))
    with pytest.raises(KafkaProtocolError, match="malformed SCRAM server"):
        client3.verify_server_final(b"v=!!!not-base64")


def test_scram_rfc7677_vector():
    """RFC 7677's published SCRAM-SHA-256 test vector, driven through both
    sides with the vector's fixed nonces/salt."""
    import base64

    from kafka_topic_analyzer_tpu.io import kafka_codec as kc

    client = kc.ScramClient("SCRAM-SHA-256", "user", "pencil")
    client.nonce = "rOprNGfwEbeRWgbNEkqO"
    client._first_bare = f"n=user,r={client.nonce}"
    server_first = (
        b"r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
        b"s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096"
    )
    final = client.final_message(server_first)
    assert final == (
        b"c=biws,r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
        b"p=dHzbZapWIk4jUhN+Ute9ytag9zjfMHgsqmmiz7AndVQ="
    )
    client.verify_server_final(
        b"v=6rriTRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4="
    )
