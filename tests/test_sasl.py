"""SASL/PLAIN authentication against a credential-enforcing fake broker."""

import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_codec import KafkaProtocolError
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

from fake_broker import FakeBroker

ROWS = [(i, 1_600_000_000_000 + i, f"k{i % 7}".encode(), bytes(20))
        for i in range(120)]

CREDS = {"security.protocol": "sasl_plaintext",
         "sasl.username": "scout", "sasl.password": "hunter2"}


def _broker():
    return FakeBroker("s.topic", {0: ROWS}, sasl_plain=("scout", "hunter2"))


def test_sasl_scan_with_good_credentials():
    with _broker() as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", "s.topic", overrides=dict(CREDS)
        )
        cfg = AnalyzerConfig(num_partitions=1, batch_size=64)
        m = run_scan("s.topic", src, CpuExactBackend(cfg, init_now_s=0), 64).metrics
        src.close()
    assert m.overall_count == 120


def test_sasl_bad_credentials_rejected():
    with _broker() as broker:
        bad = dict(CREDS, **{"sasl.password": "wrong"})
        with pytest.raises(KafkaProtocolError, match="authentication failed"):
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", "s.topic", overrides=bad
            )


def test_unauthenticated_client_gets_dropped():
    with _broker() as broker:
        # No SASL config at all: broker drops the first non-SASL request.
        with pytest.raises(KafkaProtocolError, match="closed the connection"):
            KafkaWireSource(f"127.0.0.1:{broker.port}", "s.topic")


def test_sasl_requires_credentials():
    with pytest.raises(ValueError, match="sasl.username"):
        KafkaWireSource(
            "127.0.0.1:1", "x",
            overrides={"security.protocol": "sasl_plaintext"},
        )


def test_sasl_client_against_non_sasl_broker():
    """Mismatch must surface as a clear handshake error, not a crashed
    broker thread masquerading as a dropped connection."""
    with FakeBroker("s.topic", {0: ROWS}) as broker:  # no SASL required
        with pytest.raises(KafkaProtocolError, match="SASL handshake failed"):
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", "s.topic", overrides=dict(CREDS)
            )


def test_unsupported_mechanism():
    with pytest.raises(ValueError, match="PLAIN only"):
        KafkaWireSource(
            "127.0.0.1:1", "x",
            overrides={"security.protocol": "sasl_plaintext",
                       "sasl.mechanism": "SCRAM-SHA-512",
                       "sasl.username": "u", "sasl.password": "p"},
        )
