"""Turnkey multi-host scan: two jax.distributed processes over localhost
(4 virtual CPU devices each → one global 8-device mesh) produce exactly
the metrics a single-process sharded scan produces.

This is the test the reference cannot have (it is single-threaded,
src/kafka.rs:92-135); it locks the multi-controller contract:
process-local shard feeding (mesh.local_data_rows), lockstep collective
steps with the global_any agreement round, and the collective finalize.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_CHILD = os.path.join(_HERE, "multihost_child.py")


def _cpu_multiprocess_supported() -> bool:
    """jax < 0.5's CPU backend rejects cross-process collectives outright
    ("Multiprocess computations aren't implemented on the CPU backend"),
    so the two-process emulation these tests rely on cannot run there."""
    import jax

    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return True
    return (major, minor) >= (0, 5)


pytestmark = pytest.mark.skipif(
    not _cpu_multiprocess_supported(),
    reason="installed jax cannot run multiprocess collectives on CPU",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_reference(n_partitions: int = 6) -> dict:
    """The same scan on this process's own 8-device mesh (conftest env).
    Sequential ingest — byte-identity across worker counts is exactly the
    contract the fan-in composition tests lean on (DESIGN.md §11/§14)."""
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.synthetic import (
        SyntheticSource,
        SyntheticSpec,
    )
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    spec = SyntheticSpec(
        num_partitions=n_partitions,
        messages_per_partition=5000,
        keys_per_partition=500,
        key_null_permille=50,
        tombstone_permille=100,
        seed=42,
    )
    config = AnalyzerConfig(
        num_partitions=n_partitions,
        batch_size=2048,
        count_alive_keys=True,
        alive_bitmap_bits=16,
        enable_hll=True,
        enable_quantiles=True,
        mesh_shape=(8, 1),
    )
    backend = ShardedTpuBackend(config)
    result = run_scan(
        "mh-topic", SyntheticSource(spec), backend, batch_size=2048
    )
    return result.metrics.to_dict(result.start_offsets, result.end_offsets)


def _run_children(out, extra_args):
    port = _free_port()
    env = dict(os.environ)
    # The child pins its own platform/device-count env before importing jax.
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _CHILD, str(pid), "2", str(port), str(out)]
            + extra_args,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=300)
            outs.append((p.returncode, stdout, stderr))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multi-host children timed out; partial: {outs}")
    for rc, stdout, stderr in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:{stdout}\nstderr:{stderr}"


def test_two_process_scan_matches_single_process(tmp_path):
    out = tmp_path / "mh_metrics.json"
    _run_children(out, [])
    got = json.loads(out.read_text())
    # Round-trip the reference through JSON too: quantile dict keys are
    # floats in-memory and strings on the wire.
    want = json.loads(json.dumps(_single_process_reference()))
    assert got == want


def test_two_process_scan_with_per_controller_fanin(tmp_path):
    """The PR-7 tentpole under real multi-controller: each process runs
    2-worker ParallelIngest fan-ins per data row it feeds (16 partitions,
    8 workers per controller), and the merged metrics are byte-identical
    to the sequential single-process sharded scan.  The child additionally
    asserts the per-controller resolved counts and the c0./c1.-prefixed
    worker telemetry union."""
    out = tmp_path / "mh_fanin_metrics.json"
    _run_children(out, ["workers"])
    got = json.loads(out.read_text())
    want = json.loads(json.dumps(_single_process_reference(n_partitions=16)))
    assert got == want


def test_two_process_interrupt_resume(tmp_path):
    """Per-process snapshots + resume under jax.distributed: an
    interrupted 2-process scan resumed with fresh backends produces
    exactly the single-process metrics (multi-host checkpoint/resume —
    SURVEY.md §5.4 under §5.8's multi-controller design)."""
    out = tmp_path / "mh_resume_metrics.json"
    snap = tmp_path / "snaps"
    snap.mkdir()
    _run_children(out, ["resume", str(snap)])
    got = json.loads(out.read_text())
    want = json.loads(json.dumps(_single_process_reference()))
    assert got == want
