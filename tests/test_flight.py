"""Pipeline flight recorder + scan doctor (ISSUE 10).

Four layers of coverage:

- sampler units: clock-injectable ticks, ring decimation, thread
  start/stop, Chrome counter tracks, the /flight endpoint;
- attribution scenarios: a throttled DispatchQueue (D=1, slow fake
  device) must read dispatch-bound; a starved pipeline (slow fake
  source) must read ingest-bound; verdicts must aggregate over the
  registry merge (mesh-2 scan + synthetic two-controller snapshots);
- byte-identity: scans sampled by a live recorder produce reports
  byte-identical to recorder-off, across wire × segfile × workers ×
  K × mesh (the DESIGN §9/§17 non-perturbation bar);
- CLI surfaces: --stats BOTTLENECK digest (stage timings rendered once,
  from the snapshot), --json flight block, --flight-record windows.
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.request

import pytest

from kafka_topic_analyzer_tpu.backends.base import DispatchQueue
from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.obs import doctor
from kafka_topic_analyzer_tpu.obs import flight as obs_flight
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs import trace as obs_trace
from kafka_topic_analyzer_tpu.obs.flight import FlightRecorder
from kafka_topic_analyzer_tpu.obs.registry import (
    default_registry,
    merge_snapshots,
)

pytestmark = pytest.mark.flight


@pytest.fixture(autouse=True)
def _reset_registry():
    default_registry().reset()
    yield
    default_registry().reset()
    obs_flight.set_active(None)


# ---------------------------------------------------------------------------
# sampler units


def test_recorder_samples_synchronized_tracks():
    clk = {"t": 100.0}
    rec = FlightRecorder(interval_s=0.5, clock=lambda: clk["t"])
    rec.sample_once()
    clk["t"] = 101.0
    obs_metrics.STAGE_SECONDS.labels(stage="ingest").inc(0.7)
    obs_metrics.DISPATCH_INFLIGHT.set(2)
    rec.sample_once()
    s = rec.series()
    assert s["t"] == [0.0, 1.0]
    assert s["tracks"]["stage_ingest_s"] == [0.0, 0.7]
    assert s["tracks"]["dispatch_inflight"] == [0.0, 2.0]
    assert s["kinds"]["stage_ingest_s"] == "cum"
    assert s["kinds"]["dispatch_inflight"] == "inst"
    # Every track shares the one timestamp list.
    assert all(len(v) == 2 for v in s["tracks"].values())
    assert obs_metrics.FLIGHT_SAMPLES.value == 2
    json.dumps(s)  # the /flight endpoint serves exactly this


def test_recorder_ring_decimates_and_doubles_interval():
    clk = {"t": 0.0}
    rec = FlightRecorder(interval_s=1.0, max_samples=16,
                         clock=lambda: clk["t"])
    for i in range(17):
        clk["t"] = float(i)
        rec.sample_once()
    s = rec.series()
    # 17th sample tripped the 2:1 decimation: every other sample kept,
    # interval doubled — bounded memory with full-scan coverage.
    assert len(s["t"]) == 9
    assert s["t"] == [0.0, 2.0, 4.0, 6.0, 8.0, 10.0, 12.0, 14.0, 16.0]
    assert s["interval_s"] == 2.0
    assert all(len(v) == 9 for v in s["tracks"].values())


def test_recorder_thread_start_stop():
    rec = FlightRecorder(interval_s=0.01)
    rec.start()
    with pytest.raises(RuntimeError):
        rec.start()
    time.sleep(0.08)
    rec.stop()  # takes the closing sample
    n = len(rec.series()["t"])
    assert n >= 2
    time.sleep(0.03)
    assert len(rec.series()["t"]) == n  # sampler actually stopped
    rec.stop()  # idempotent (one more closing sample, no thread)


def test_recorder_emits_chrome_counter_tracks():
    tracer = obs_trace.SpanTracer()
    obs_trace.set_active(tracer)
    try:
        rec = FlightRecorder(interval_s=0.5, clock=lambda: 0.0)
        obs_metrics.DISPATCH_INFLIGHT.set(1)
        rec.sample_once()
    finally:
        obs_trace.set_active(None)
    counters = [e for e in tracer.events() if e["ph"] == "C"]
    assert len(counters) == 1
    ev = counters[0]
    assert ev["name"] == "flight"
    # Instantaneous lanes only — cumulative ramps stay in /flight.
    assert ev["args"]["dispatch_inflight"] == 1.0
    assert "stage_ingest_s" not in ev["args"]
    # Counter events must coexist with spans in one valid trace doc.
    json.dumps(tracer.chrome_trace())


def test_flight_endpoint_serves_active_series():
    from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter

    exporter = PrometheusExporter(0)
    try:
        url = f"http://127.0.0.1:{exporter.port}/flight"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=5)
        assert ei.value.code == 404  # no recorder active
        rec = FlightRecorder(interval_s=0.5, clock=lambda: 0.0)
        rec.sample_once()
        obs_flight.set_active(rec)
        with urllib.request.urlopen(url, timeout=5) as resp:
            doc = json.loads(resp.read().decode())
        assert doc["t"] == [0.0]
        assert "stage_ingest_s" in doc["tracks"]
        # /metrics still serves, now including the recorder's own counter.
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
        ) as resp:
            text = resp.read().decode()
        assert "kta_flight_samples_total 1" in text
    finally:
        obs_flight.set_active(None)
        exporter.close()


# ---------------------------------------------------------------------------
# throttle-wait booking (satellite: booked with the recorder OFF)


class _SlowToken:
    """Fake device-completion token: jax.block_until_ready calls the
    leaf's block_until_ready method, which is where a real device queue
    would wait."""

    def __init__(self, dt: float):
        self._dt = dt
        self._done = False

    def is_ready(self) -> bool:
        return self._done

    def block_until_ready(self) -> "._SlowToken":
        time.sleep(self._dt)
        self._done = True
        return self


def test_throttle_wait_booked_without_recorder():
    q = DispatchQueue(1)
    q.throttle()  # empty queue: no wait, no booking
    assert obs_metrics.DISPATCH_THROTTLE_SECONDS.value == 0.0
    q.launched(_SlowToken(0.05), batches=1)
    q.throttle()  # full at depth 1: must retire the slow token first
    waited = obs_metrics.DISPATCH_THROTTLE_SECONDS.value
    assert waited >= 0.04
    q.launched(_SlowToken(0.0), batches=1)
    q.drain()
    # drain() is not a launch-site throttle; it books nothing more.
    assert obs_metrics.DISPATCH_THROTTLE_SECONDS.value == waited


# ---------------------------------------------------------------------------
# attribution scenarios (acceptance: known-bound configurations)


def _spec(n=400, parts=2):
    return SyntheticSpec(
        num_partitions=parts, messages_per_partition=n,
        keys_per_partition=50,
    )


def _cfg(parts=2, **kw):
    return AnalyzerConfig(num_partitions=parts, batch_size=128, **kw)


class _SlowDeviceBackend(CpuExactBackend):
    """Superbatch-capable oracle whose 'device' retires slowly: D=1 means
    every second flush blocks in DispatchQueue.throttle — the canonical
    dispatch-bound shape."""

    superbatch_k = 2

    def __init__(self, config, device_dt=0.02, **kw):
        super().__init__(config, **kw)
        self._dq = DispatchQueue(1)
        self._device_dt = device_dt

    def update_superbatch(self, items) -> None:
        self._dq.throttle()
        for b in items:
            self.update(b)
        self._dq.launched(_SlowToken(self._device_dt), len(items))

    def drain_dispatch(self) -> None:
        self._dq.drain()


class _SlowSource:
    """Source wrapper that starves the pipeline: every yielded batch
    costs a sleep on the producing thread — the canonical ingest-bound
    shape.  Forwards the full RecordSource surface."""

    def __init__(self, inner, dt=0.01):
        self._inner = inner
        self._dt = dt

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def batches(self, batch_size, partitions=None, start_at=None):
        for b in self._inner.batches(
            batch_size, partitions=partitions, start_at=start_at
        ):
            time.sleep(self._dt)
            yield b


def test_dispatch_bound_scenario_yields_dispatch_bound():
    result = run_scan(
        "synth", SyntheticSource(_spec(n=600)),
        _SlowDeviceBackend(_cfg(), init_now_s=10**10), 128,
    )
    d = doctor.diagnose(result.telemetry,
                        dispatch_depth=1)
    assert d.verdict == "dispatch-bound"
    # The decisive signal: real backpressure wait at the launch site.
    assert d.evidence["throttle_wait"] > 0.2
    assert d.stages["dispatch"] > 0.5


def test_ingest_bound_scenario_yields_ingest_bound():
    result = run_scan(
        "synth", _SlowSource(SyntheticSource(_spec(n=600))),
        CpuExactBackend(_cfg(), init_now_s=10**10), 128,
    )
    d = doctor.diagnose(result.telemetry)
    assert d.verdict == "ingest-bound"
    assert d.stages["ingest"] > 0.5
    assert d.evidence["throttle_wait"] == 0.0


def test_ingest_bound_evidence_with_recorder_and_workers():
    """Parallel ingest + a live recorder: the workers stay busy (not
    stalled), the fan-in queues sample empty, and the windowed verdicts
    agree with the headline."""
    rec = FlightRecorder(interval_s=0.005)
    obs_flight.set_active(rec)
    rec.start()
    try:
        result = run_scan(
            "synth",
            _SlowSource(SyntheticSource(_spec(n=600, parts=4)), dt=0.005),
            CpuExactBackend(_cfg(parts=4), init_now_s=10**10), 128,
            ingest_workers=2,
        )
    finally:
        rec.stop()
        obs_flight.set_active(None)
    d = doctor.diagnose(result.telemetry, flight=rec.series())
    assert d.verdict == "ingest-bound"
    assert d.evidence["worker_busy"] > 0.5
    assert d.evidence["queue_empty"] > 0.5
    assert d.window_share.get("ingest-bound", 0) > 0.5
    assert d.windows  # the timeline rode along


def test_verdict_aggregates_across_controller_snapshots():
    """The fleet verdict is computed from merge_snapshots output: two
    controllers, both ingest-heavy, one with a busier dispatch — counters
    sum, so the merged occupancy is the fleet occupancy."""

    def snap(ingest_s, dispatch_s, throttle_s=0.0):
        return {
            "kta_stage_seconds_total": {
                "type": "counter", "help": "",
                "samples": [
                    {"labels": {"stage": "ingest"}, "value": ingest_s},
                    {"labels": {"stage": "dispatch"}, "value": dispatch_s},
                ],
            },
            "kta_dispatch_throttle_seconds_total": {
                "type": "counter", "help": "",
                "samples": [{"labels": {}, "value": throttle_s}],
            },
        }

    merged = merge_snapshots([snap(8.0, 1.0), snap(6.0, 3.0)])
    d = doctor.diagnose(merged, controllers=2)
    assert d.controllers == 2
    assert d.verdict == "ingest-bound"
    assert d.stage_seconds == {"ingest": 14.0, "dispatch": 4.0}
    assert abs(d.stages["ingest"] - 14.0 / 18.0) < 1e-9
    # Flip controller 1 to a throttled dispatch regime: the fleet verdict
    # follows the summed seconds, not either process alone.
    merged2 = merge_snapshots([snap(1.0, 9.0, 6.0), snap(2.0, 8.0, 5.0)])
    d2 = doctor.diagnose(merged2, controllers=2)
    assert d2.verdict == "dispatch-bound"
    assert d2.evidence["throttle_wait"] > 0.5


def test_mesh2_scan_verdict_aggregates():
    """Acceptance: verdicts aggregate correctly on a mesh-2 scan — the
    sharded backend's gather_telemetry feeds the doctor the same counter
    algebra, and a starved mesh still reads ingest-bound."""
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg = _cfg(parts=4, mesh_shape=(2, 1))
    # dt must outweigh the sharded step's jit compile (which honestly
    # books to dispatch on this virtual-CPU mesh, ~0.3s): at 0.1s per
    # batch x ~10 rounds the source starves the scan decisively.
    result = run_scan(
        "synth",
        _SlowSource(SyntheticSource(_spec(n=600, parts=4)), dt=0.1),
        ShardedTpuBackend(cfg, init_now_s=10**10), 128,
    )
    d = doctor.diagnose(
        result.telemetry,
        controllers=max(1, len(result.ingest_workers_per_controller)),
    )
    assert d.verdict == "ingest-bound"
    assert d.stages["ingest"] > 0.5


def test_doctor_no_signal_on_empty_snapshot():
    d = doctor.diagnose({})
    assert d.verdict == "no-signal"
    assert d.windows == [] and d.window_share == {}
    json.dumps(d.as_dict())


# ---------------------------------------------------------------------------
# byte-identity: recorder on/off (wire × segfile × workers × K × mesh)


def _full_doc(result) -> dict:
    return {
        "metrics": result.metrics.to_dict(
            result.start_offsets, result.end_offsets
        ),
        "start": result.start_offsets,
        "end": result.end_offsets,
        "degraded": result.degraded_partitions,
        "corrupt": result.corrupt_partitions,
    }


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 29}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


N_PARTS, N_REC = 4, 300
WIRE_CFG = AnalyzerConfig(
    num_partitions=N_PARTS, batch_size=128,
    count_alive_keys=True, alive_bitmap_bits=16,
    enable_hll=True, hll_p=8,
)


def _wire_scan(recorder: bool, workers=1, superbatch=1, mesh=None):
    """Recorder-on scans run the FULL service-observability stack —
    flight ring + disk-backed history + alert-engine evaluation — so
    the identity matrix proves ISSUE 15's bar (history/alerts on vs
    off byte-identical) on the same cells that proved ISSUE 10's."""
    import tempfile

    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import DispatchConfig
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
    from kafka_topic_analyzer_tpu.obs import health as obs_health
    from kafka_topic_analyzer_tpu.obs import history as obs_history
    from fake_broker import FakeBroker

    records = {p: _mk_records(p, N_REC) for p in range(N_PARTS)}
    cfg = WIRE_CFG
    backend_cls = TpuBackend
    if mesh is not None:
        from kafka_topic_analyzer_tpu.parallel.sharded import (
            ShardedTpuBackend,
        )

        cfg = dataclasses.replace(WIRE_CFG, mesh_shape=mesh)
        backend_cls = ShardedTpuBackend
    rec = None
    store = None
    if recorder:
        from kafka_topic_analyzer_tpu.config import HealthConfig
        from kafka_topic_analyzer_tpu.obs.health import HealthEngine
        from kafka_topic_analyzer_tpu.obs.history import HistoryStore

        rec = FlightRecorder(interval_s=0.002)
        store = HistoryStore(tempfile.mkdtemp(prefix="kta-hist-"))
        rec.attach_history(store)
        obs_history.set_active(store)
        obs_health.set_active(
            HealthEngine(cfg=HealthConfig(eval_interval_s=0.005))
        )
        obs_flight.set_active(rec)
        rec.start()
    try:
        with FakeBroker("flight.topic", records,
                        max_records_per_fetch=60) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", "flight.topic",
                overrides={"retry.backoff.ms": "5"},
            )
            result = run_scan(
                "flight.topic", src,
                backend_cls(cfg, init_now_s=10**10,
                            dispatch=DispatchConfig(superbatch=superbatch)),
                cfg.batch_size, ingest_workers=workers,
            )
            src.close()
    finally:
        if rec is not None:
            rec.stop()
            obs_flight.set_active(None)
            obs_health.set_active(None)
        if store is not None:
            store.close()
            obs_history.set_active(None)
    if rec is not None:
        assert len(rec.series()["t"]) >= 1
        assert len(store.window()["t"]) >= 1  # history rode the ticks
    return _full_doc(result)


@pytest.fixture(scope="module")
def wire_baseline():
    default_registry().reset()
    return _wire_scan(recorder=False)


@pytest.mark.parametrize("workers,superbatch", [
    (1, 1), (4, 1), (1, 4), (4, 4),
])
def test_recorder_scan_identity_wire(wire_baseline, workers, superbatch):
    got = _wire_scan(recorder=True, workers=workers, superbatch=superbatch)
    assert got == wire_baseline


@pytest.mark.parametrize("mesh,workers,superbatch", [
    ((2, 1), 1, 1), ((2, 1), 2, 4),
])
def test_recorder_scan_identity_mesh(wire_baseline, mesh, workers,
                                     superbatch):
    got = _wire_scan(recorder=True, workers=workers,
                     superbatch=superbatch, mesh=mesh)
    assert got == wire_baseline


@pytest.mark.parametrize("workers,superbatch", [(1, 1), (2, 4)])
def test_recorder_scan_identity_segfile(tmp_path, workers, superbatch):
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import DispatchConfig
    from kafka_topic_analyzer_tpu.io.segfile import (
        SegmentDumpWriter,
        SegmentFileSource,
    )

    spec = SyntheticSpec(
        num_partitions=3, messages_per_partition=700,
        keys_per_partition=40, seed=5, key_null_permille=60,
        tombstone_permille=90,
    )
    d = str(tmp_path / "segs")
    writer = SegmentDumpWriter(d, "seg.topic", records_per_chunk=256)
    src = SyntheticSource(spec)
    writer.set_base_offsets(src.watermarks()[0])
    for b in src.batches(180):
        writer.append(b)
    writer.close()
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=128, count_alive_keys=True,
        alive_bitmap_bits=14,
    )

    def scan(recorder: bool):
        rec = None
        if recorder:
            rec = FlightRecorder(interval_s=0.002)
            obs_flight.set_active(rec)
            rec.start()
        try:
            s = SegmentFileSource(d, "seg.topic")
            r = run_scan(
                "seg.topic", s,
                TpuBackend(cfg, init_now_s=10**10,
                           dispatch=DispatchConfig(superbatch=superbatch)),
                128, ingest_workers=workers,
            )
            return _full_doc(r)
        finally:
            if rec is not None:
                rec.stop()
                obs_flight.set_active(None)

    assert scan(recorder=True) == scan(recorder=False)


# ---------------------------------------------------------------------------
# CLI surfaces


def _cli(capsys, extra):
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "flight.synth", "--source", "synthetic",
        "--synthetic", "partitions=2,messages=400,keys=40",
        "--quiet", *extra,
    ])
    assert rc == 0
    return capsys.readouterr()


def test_cli_stats_bottleneck_digest_and_single_stage_block(capsys):
    cap = _cli(capsys, ["--stats"])
    # The doctor's digest renders even without --flight-record (the
    # attribution inputs are always-booked counters) ...
    assert "BOTTLENECK: " in cap.err
    assert "occupancy: " in cap.err
    # ... and stage timings appear exactly ONCE, rendered from the same
    # registry snapshot the doctor used (the old duplicate in-process
    # profile print is gone).
    assert cap.err.count("scan stages:") == 1
    assert "ingest:" in cap.err
    # No recorder -> no windowed timeline line.
    assert "windows: " not in cap.err


def test_cli_flight_record_windows_and_json_block(capsys):
    cap = _cli(capsys, ["--stats", "--flight-record", "--json"])
    assert "BOTTLENECK: " in cap.err
    doc = json.loads(cap.out.splitlines()[-1])
    flight = doc["flight"]
    assert flight["verdict"]
    assert isinstance(flight["stages"], dict)
    assert isinstance(flight["windows"], list)
    # The raw ring series stays on /flight, never in --json.
    assert "series" not in flight
    json.dumps(flight)


def test_cli_json_flight_block_without_recorder(capsys):
    cap = _cli(capsys, ["--json"])
    doc = json.loads(cap.out.splitlines()[-1])
    assert doc["flight"]["verdict"]
    assert doc["flight"]["windows"] == []
