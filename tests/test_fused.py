"""Fused native decode→pack (ISSUE 8): one GIL-released C++ pass from
fetch bytes to wire-v4 rows.

The byte-identity bar has two layers:

- ROW bytes: a FusedPackSink row must equal ``pack_batch`` over the same
  records (greedy batch_size boundaries), for every feature combination —
  the sink's incremental dedupe/HLL/extreme commits cannot skew from the
  one-shot packer.
- SCAN results: a fused scan's metrics/corruption/quarantine/resume
  surfaces must equal the chained scan's across (source × workers × mesh
  × K), including injected corruption and forced fallbacks.
"""

from __future__ import annotations

import os
import random
import subprocess
import sys

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    CorruptionConfig,
    DispatchConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource, _chunk_to_batch
from kafka_topic_analyzer_tpu.io.native import (
    decode_record_set_native,
    native_available,
)
from kafka_topic_analyzer_tpu.obs.registry import default_registry
from kafka_topic_analyzer_tpu.packing import (
    FusedPackSink,
    PackedRow,
    fused_ingest_enabled,
    pack_batch,
    pack_chunks,
)
from kafka_topic_analyzer_tpu.records import RecordBatch

from fake_broker import CorruptionInjector, FakeBroker

pytestmark = [
    pytest.mark.fused,
    pytest.mark.skipif(
        not native_available(), reason="native shim unavailable"
    ),
]

TOPIC = "fused.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 29}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


N_PARTS = 4
N_REC = 300
RECORDS = {p: _mk_records(p, N_REC) for p in range(N_PARTS)}

CFG = AnalyzerConfig(
    num_partitions=N_PARTS, batch_size=128,
    count_alive_keys=True, alive_bitmap_bits=16,
    enable_hll=True, hll_p=8,
)


@pytest.fixture
def no_fused(monkeypatch):
    monkeypatch.setenv("KTA_DISABLE_FUSED", "1")


def _full_doc(result) -> dict:
    return {
        "metrics": result.metrics.to_dict(
            result.start_offsets, result.end_offsets
        ),
        "start": result.start_offsets,
        "end": result.end_offsets,
        "degraded": result.degraded_partitions,
        "corrupt": result.corrupt_partitions,
    }


def _fused_counters() -> "dict[str, float]":
    snap = default_registry().snapshot()
    out: "dict[str, float]" = {}
    for name in (
        "kta_fused_batches_total",
        "kta_fused_records_total",
    ):
        m = snap.get(name)
        out[name] = sum(s["value"] for s in m["samples"]) if m else 0.0
    m = snap.get("kta_fused_fallback_total")
    for s in (m["samples"] if m else []):
        out[f"fallback:{s['labels']['reason']}"] = s["value"]
    return out


def _counter_delta(before, after) -> "dict[str, float]":
    return {
        k: after.get(k, 0.0) - before.get(k, 0.0)
        for k in set(before) | set(after)
        if after.get(k, 0.0) != before.get(k, 0.0)
    }


# ---------------------------------------------------------------------------
# row-level byte identity


def _random_stream(seed: int, n: int, parts: int) -> RecordBatch:
    rng = np.random.default_rng(seed)
    key_null = rng.random(n) < 0.1
    value_null = rng.random(n) < 0.15
    batch = RecordBatch(
        partition=np.sort(rng.integers(0, parts, n).astype(np.int32)),
        key_len=np.where(key_null, 0, rng.integers(0, 40, n)).astype(np.int32),
        value_len=np.where(value_null, 0, rng.integers(0, 500, n)).astype(np.int32),
        key_null=key_null,
        value_null=value_null,
        ts_s=rng.integers(0, 2**31, n),
        key_hash32=rng.integers(0, 2**32, n, dtype=np.uint32),
        key_hash64=rng.integers(0, 2**63, n, dtype=np.uint64),
        valid=np.ones(n, dtype=bool),
    )
    batch.key_hash32[key_null] = 0
    batch.key_hash64[key_null] = 0
    batch.offsets = np.arange(n, dtype=np.int64)
    return batch


@pytest.mark.parametrize(
    "kw",
    [
        {},
        {"count_alive_keys": True, "alive_bitmap_bits": 12},
        {"count_alive_keys": True, "alive_bitmap_bits": 16,
         "enable_hll": True, "hll_p": 6},      # table mode
        {"enable_hll": True, "hll_p": 14},      # pair mode at B=64
    ],
)
def test_fused_rows_equal_pack_batch(kw):
    """Columns appended per single-partition run produce rows byte-equal
    to the chained greedy resplit + pack_batch — every section, every
    feature combination, including the partial final row."""
    b = 64
    cfg = AnalyzerConfig(num_partitions=5, batch_size=b, **kw)
    full = _random_stream(seed=1, n=1000, parts=5)

    chain = []
    lo = 0
    while lo < len(full):
        hi = min(lo + b, len(full))
        chain.append(pack_batch(full.slice(lo, hi), cfg))
        lo = hi

    sink = FusedPackSink(cfg, b, dense_of=lambda p: p)
    rows = []
    i = 0
    part = full.partition
    while i < len(full):
        j = i
        while j < len(full) and part[j] == part[i]:
            j += 1
        sink.append_batch(full.slice(i, j), reason="frame-fallback")
        rows.extend(r.buf for r in sink.take_completed())
        i = j
    sink.flush()
    rows.extend(r.buf for r in sink.take_completed())

    assert len(rows) == len(chain)
    for k, (a, c) in enumerate(zip(rows, chain)):
        assert np.array_equal(a, c), f"row {k} differs"


def _encode_stream(seed: int, frames: int):
    """Multi-frame single-partition record set with nulls, tombstones,
    offset gaps (compaction), and record headers."""
    rng = random.Random(seed)
    off = 5
    parts = []
    for _ in range(frames):
        rows = []
        for _ in range(rng.randrange(1, 40)):
            key = (
                None if rng.random() < 0.1
                else bytes(rng.randrange(0, 256) for _ in range(rng.randrange(0, 12)))
            )
            val = None if rng.random() < 0.15 else b"v" * rng.randrange(0, 50)
            rows.append((off, rng.randrange(0, 2**41), key, val))
            off += rng.randrange(1, 3)
        parts.append(kc.encode_record_batch(rows))
    return b"".join(parts), off


@pytest.mark.parametrize("batch_size", [16, 64, 1024])
def test_fused_decode_rows_equal_chain(batch_size):
    """The fused record-set decode produces the same rows (and the same
    consumed/covered/acceptance bookkeeping) as decode_record_set_native →
    window filter → pack_batch — including frames spanning row
    boundaries at small batch sizes."""
    cfg = AnalyzerConfig(
        num_partitions=4, batch_size=batch_size,
        count_alive_keys=True, alive_bitmap_bits=10,
        enable_hll=True, hll_p=6,
    )
    data, end_off = _encode_stream(seed=7, frames=9)
    a, bwin = 9, end_off - 3  # clip the window on both sides

    soa, used, covered = decode_record_set_native(data)
    offs = soa["offsets"]
    lo = int(np.searchsorted(offs, a, "left"))
    hi = int(np.searchsorted(offs, bwin, "left"))
    batch = _chunk_to_batch(soa, slice(lo, hi), 9)
    batch.partition = np.full(hi - lo, 2, dtype=np.int32)  # dense remap
    chain = []
    loi = 0
    while loi < hi - lo:
        hii = min(loi + batch_size, hi - lo)
        chain.append(pack_batch(batch.slice(loi, hii), cfg))
        loi = hii

    sink = FusedPackSink(cfg, batch_size, dense_of=lambda p: 2)
    cnt, used2, covered2, last = sink.append_record_set(data, a, bwin, 9)
    rows = [r.buf for r in sink.take_completed()]
    sink.flush()
    rows.extend(r.buf for r in sink.take_completed())

    assert (cnt, used2, covered2) == (hi - lo, used, covered)
    assert last == int(offs[hi - 1])
    assert len(rows) == len(chain)
    for k, (x, c) in enumerate(zip(rows, chain)):
        assert np.array_equal(x, c), f"row {k} differs"


def test_fused_sharded_rows_equal_pack_chunks():
    """Sharded-form rows ([S, chunk_nbytes]) equal pack_chunks over the
    corresponding row batch — the prepare_shard staging contract."""
    cfg = AnalyzerConfig(num_partitions=3, batch_size=64,
                         count_alive_keys=True, alive_bitmap_bits=10)
    import dataclasses

    chunk_cfg = dataclasses.replace(cfg, batch_size=32)
    full = _random_stream(seed=3, n=200, parts=3)
    chain = []
    lo = 0
    while lo < len(full):
        hi = min(lo + 64, len(full))
        chain.append(pack_chunks(full.slice(lo, hi), chunk_cfg, 2))
        lo = hi

    sink = FusedPackSink(chunk_cfg, 32, dense_of=lambda p: p,
                         space_shards=2, chunk_rows=True)
    rows = []
    part = full.partition
    i = 0
    while i < len(full):
        j = i
        while j < len(full) and part[j] == part[i]:
            j += 1
        sink.append_batch(full.slice(i, j), reason="frame-fallback")
        rows.extend(r.buf for r in sink.take_completed())
        i = j
    sink.flush()
    rows.extend(r.buf for r in sink.take_completed())
    assert len(rows) == len(chain)
    for k, (x, c) in enumerate(zip(rows, chain)):
        assert x.shape == c.shape and np.array_equal(x, c), f"row {k}"


def test_pack_range_violation_raises_packers_error():
    """A decoded record the wire-v4 layout cannot carry raises the SAME
    ValueError the numpy packer would (key > 64 KiB)."""
    rows = [(5, 1000, b"k" * 70_000, b"v")]
    data = kc.encode_record_batch(rows)
    cfg = AnalyzerConfig(num_partitions=1, batch_size=16)
    sink = FusedPackSink(cfg, 16, dense_of=lambda p: 0)
    with pytest.raises(ValueError, match="key length 70000 exceeds"):
        sink.append_record_set(data, 0, 10**9, 0)


def test_pack_range_outside_window_is_filtered_not_raised():
    """Chained parity: a record OUTSIDE [min_off, max_off) never reaches
    the packer, so an oversized key there must not abort the fused scan
    either — in-window records of the same frame still pack.  Covers both
    the rewind path and the spanning-frame pre-validation path."""
    rows = [(5, 1000, b"ok", b"v"), (6, 1000, b"k" * 70_000, b"v")]
    data = kc.encode_record_batch(rows)
    cfg = AnalyzerConfig(num_partitions=1, batch_size=16)
    sink = FusedPackSink(cfg, 16, dense_of=lambda p: 0)
    cnt, used, covered, last = sink.append_record_set(data, 0, 6, 0)
    assert (cnt, used, last) == (1, len(data), 5)
    # Spanning-frame pre-validation path: batch_size 1 forces the frame
    # through validate_frame_records.
    sink2 = FusedPackSink(cfg, 1, dense_of=lambda p: 0)
    cnt2, used2, _, last2 = sink2.append_record_set(data, 0, 6, 0)
    assert (cnt2, used2, last2) == (1, len(data), 5)


# ---------------------------------------------------------------------------
# scan-level identity (wire)


def _wire_scan(workers=1, superbatch=1, backend_cls=TpuBackend,
               cfg=CFG, records=RECORDS, **source_kw):
    with FakeBroker(TOPIC, records, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC,
            overrides=dict(FAST_RETRY), **source_kw,
        )
        backend = backend_cls(
            cfg, init_now_s=10**10,
            dispatch=DispatchConfig(superbatch=superbatch),
        )
        result = run_scan(
            TOPIC, src, backend, cfg.batch_size, ingest_workers=workers
        )
        src.close()
    return result


@pytest.fixture(scope="module")
def wire_baseline():
    """Chained (fused disabled) sequential scan — the byte-exact referee."""
    os.environ["KTA_DISABLE_FUSED"] = "1"
    try:
        result = _wire_scan()
    finally:
        os.environ.pop("KTA_DISABLE_FUSED", None)
    return _full_doc(result)


@pytest.mark.parametrize("workers,superbatch", [
    (1, 1), (4, 1), (1, 4), (4, 4),
])
def test_fused_wire_scan_identical(wire_baseline, workers, superbatch):
    before = _fused_counters()
    result = _wire_scan(workers=workers, superbatch=superbatch)
    assert _full_doc(result) == wire_baseline
    delta = _counter_delta(before, _fused_counters())
    # Every record of this clean scan took the fused path.
    assert delta.get("kta_fused_records_total", 0) == N_PARTS * N_REC


@pytest.mark.parametrize("mesh,workers", [((2, 1), 1), ((2, 1), 2),
                                          ((2, 2), 1)])
def test_fused_sharded_scan_identical(wire_baseline, mesh, workers):
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend
    import dataclasses

    cfg = dataclasses.replace(CFG, mesh_shape=mesh)
    result = _wire_scan(workers=workers, cfg=cfg,
                        backend_cls=ShardedTpuBackend)
    assert _full_doc(result) == wire_baseline


def test_fused_compressed_frames_fall_back_identically(no_fused):
    """gzip record sets can't take the fused walk: records reach the rows
    through the per-frame chain — booked on the fallback counter, with
    scan results still identical to the fully-chained scan."""
    chained = _wire_scan(records=RECORDS)
    del os.environ["KTA_DISABLE_FUSED"]

    def gz_scan():
        with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60,
                        compression=kc.COMPRESSION_GZIP) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            )
            r = run_scan(TOPIC, src, TpuBackend(CFG, init_now_s=10**10), 128)
            src.close()
        return r

    before = _fused_counters()
    fused_gz = gz_scan()
    delta = _counter_delta(before, _fused_counters())
    assert _full_doc(fused_gz) == _full_doc(chained)
    # Nothing decodes natively in a compressed stream: every record is a
    # booked fallback, never silent.
    assert delta.get("fallback:frame-fallback", 0) == N_PARTS * N_REC


def test_forced_fallback_books_reason(no_fused):
    """KTA_DISABLE_FUSED: the scan runs the chained path and books the
    stream-level bypass."""
    before = _fused_counters()
    result = _wire_scan()
    delta = _counter_delta(before, _fused_counters())
    assert result.metrics is not None
    assert delta.get("fallback:fused-disabled", 0) >= 1
    assert delta.get("kta_fused_records_total", 0) == 0


def test_fused_scan_from_offsets_identical(wire_baseline):
    """start_at resume composes: a fused scan from mid-stream offsets
    equals the chained scan from the same offsets."""
    start_at = {p: N_REC // 3 for p in range(N_PARTS)}

    def scan(disable):
        if disable:
            os.environ["KTA_DISABLE_FUSED"] = "1"
        try:
            with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
                src = KafkaWireSource(
                    f"127.0.0.1:{broker.port}", TOPIC,
                    overrides=dict(FAST_RETRY),
                )
                r = run_scan(TOPIC, src, TpuBackend(CFG, init_now_s=10**10),
                             128, start_at=start_at)
                src.close()
            return _full_doc(r)
        finally:
            os.environ.pop("KTA_DISABLE_FUSED", None)

    assert scan(disable=False) == scan(disable=True)


# ---------------------------------------------------------------------------
# corruption parity


def test_fused_corruption_classification_parity(tmp_path):
    """Deterministic poison under --on-corruption=quarantine: the fused
    scan classifies, accounts, and quarantines EXACTLY like the chained
    scan (same taxonomy kinds, same sidecars, same resume spans)."""
    def poisoned():
        inj = (
            CorruptionInjector()
            .flip_byte(1, chunk=1, offset=-1)
            .flip_byte(2, chunk=3, offset=-3)
        )
        return FakeBroker(
            TOPIC, RECORDS, max_records_per_fetch=50, corruption=inj,
            honor_partition_max_bytes=True,
        )

    def run(disable, qdir):
        if disable:
            os.environ["KTA_DISABLE_FUSED"] = "1"
        try:
            with poisoned() as broker:
                src = KafkaWireSource(
                    f"127.0.0.1:{broker.port}", TOPIC,
                    overrides=dict(FAST_RETRY, **{"check.crcs": "true"}),
                    corruption=CorruptionConfig(
                        policy="quarantine", quarantine_dir=qdir
                    ),
                )
                r = run_scan(TOPIC, src, TpuBackend(CFG, init_now_s=10**10),
                             128)
                spans = src.corruption_spans()
                src.close()
            return _full_doc(r), spans
        finally:
            os.environ.pop("KTA_DISABLE_FUSED", None)

    chain_doc, chain_spans = run(True, str(tmp_path / "qc"))
    before = _fused_counters()
    fused_doc, fused_spans = run(False, str(tmp_path / "qf"))
    delta = _counter_delta(before, _fused_counters())
    # The poisoned scan must have actually taken the fused path for the
    # clean frames (and booked the salvaged remainder as fallbacks).
    assert delta.get("kta_fused_records_total", 0) > 0
    assert fused_doc == chain_doc
    assert sorted(fused_doc["corrupt"]) == [1, 2]
    assert fused_spans == chain_spans
    assert sorted(os.listdir(tmp_path / "qf")) == sorted(
        os.listdir(tmp_path / "qc")
    )


# ---------------------------------------------------------------------------
# segfile cold path


def test_fused_segfile_scan_identical(tmp_path):
    from kafka_topic_analyzer_tpu.io.segfile import (
        SegmentDumpWriter,
        SegmentFileSource,
    )
    from kafka_topic_analyzer_tpu.io.synthetic import (
        SyntheticSource,
        SyntheticSpec,
    )

    spec = SyntheticSpec(
        num_partitions=3, messages_per_partition=700, keys_per_partition=40,
        seed=5, key_null_permille=60, tombstone_permille=90,
    )
    d = str(tmp_path / "segs")
    writer = SegmentDumpWriter(d, "seg.topic", records_per_chunk=256)
    src = SyntheticSource(spec)
    writer.set_base_offsets(src.watermarks()[0])
    for b in src.batches(180):
        writer.append(b)
    writer.close()
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=128, count_alive_keys=True,
        alive_bitmap_bits=14, enable_hll=True, hll_p=8,
    )

    def scan(disable, workers=1):
        if disable:
            os.environ["KTA_DISABLE_FUSED"] = "1"
        try:
            s = SegmentFileSource(d, "seg.topic")
            r = run_scan("seg.topic", s, TpuBackend(cfg, init_now_s=10**10),
                         128, ingest_workers=workers)
            return _full_doc(r)
        finally:
            os.environ.pop("KTA_DISABLE_FUSED", None)

    base = scan(disable=True)
    assert scan(disable=False) == base
    assert scan(disable=False, workers=2) == base


# ---------------------------------------------------------------------------
# no hard native dependency


def test_scan_with_native_disabled_subprocess():
    """KTA_DISABLE_NATIVE: the whole stack (engine gate included) runs the
    pure-python chain — the fused path is an optimization, never a
    dependency."""
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu';"
        "from kafka_topic_analyzer_tpu.io.native import native_status;"
        "from kafka_topic_analyzer_tpu.packing import fused_ingest_enabled;"
        "ok, why = native_status();"
        "assert not ok and why == 'disabled', (ok, why);"
        "assert not fused_ingest_enabled();"
        "from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec;"
        "from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend;"
        "from kafka_topic_analyzer_tpu.config import AnalyzerConfig;"
        "from kafka_topic_analyzer_tpu.engine import run_scan;"
        "spec = SyntheticSpec(num_partitions=2, messages_per_partition=50, keys_per_partition=9, seed=3);"
        "cfg = AnalyzerConfig(num_partitions=2, batch_size=32);"
        "r = run_scan('t', SyntheticSource(spec), CpuExactBackend(cfg, init_now_s=0), 32);"
        "assert r.metrics.overall_count == 100, r.metrics.overall_count"
    )
    env = dict(os.environ, KTA_DISABLE_NATIVE="1")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True,
        timeout=240,
    )
    assert proc.returncode == 0, proc.stderr


def test_fused_gate_requires_sink_capable_batches_signature():
    """Wrappers that __getattr__-forward supports_fused_sink but override
    batches() without the sink parameter must not be offered one."""
    class Wrapper:
        def __init__(self, inner):
            self.inner = inner

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def batches(self, batch_size, partitions=None, start_at=None):
            yield from self.inner.batches(batch_size, partitions, start_at)

    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        inner = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        src = Wrapper(inner)
        assert src.supports_fused_sink  # forwarded — the trap this guards
        result = run_scan(TOPIC, src, TpuBackend(CFG, init_now_s=10**10), 128)
        inner.close()
    assert result.metrics.overall_count == sum(
        1 for rows in RECORDS.values() for r in rows if r[3] is not None
    ) or result.metrics.overall_count > 0


def test_packed_row_bookkeeping():
    """PackedRow carries what the engine reads off decoded batches:
    num_valid/nbytes duck-typing and per-partition progress."""
    cfg = AnalyzerConfig(num_partitions=2, batch_size=32)
    sink = FusedPackSink(cfg, 32, dense_of=lambda p: p)
    full = _random_stream(seed=4, n=40, parts=1)
    full.offsets = np.arange(100, 140, dtype=np.int64)
    sink.append_batch(full, reason="frame-fallback")
    rows = sink.take_completed()
    sink.flush()
    rows += sink.take_completed()
    assert [r.num_valid for r in rows] == [32, 8]
    assert rows[0].next_offsets == {0: 132}
    assert rows[1].next_offsets == {0: 140}
    assert rows[0].nbytes == 32 * sum(
        np.dtype(dt).itemsize for _, dt in RecordBatch.FIELDS
    )
    assert all(isinstance(r, PackedRow) for r in rows)
    assert fused_ingest_enabled()
