"""TLS wire-client tests against a TLS-wrapped fake broker (self-signed
cert generated with the openssl CLI)."""

import ssl
import subprocess

import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

from fake_broker import FakeBroker


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    key, cert = d / "key.pem", d / "cert.pem"
    try:
        subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048",
                "-keyout", str(key), "-out", str(cert),
                "-days", "1", "-nodes",
                "-subj", "/CN=127.0.0.1",
                "-addext", "subjectAltName=IP:127.0.0.1",
            ],
            check=True, capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError):
        pytest.skip("openssl CLI unavailable")
    return str(key), str(cert)


def _tls_broker(certs):
    key, cert = certs
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    rows = [(i, 1_600_000_000_000 + i, f"k{i % 5}".encode(), bytes(10 + i % 20))
            for i in range(200)]
    return FakeBroker("tls.topic", {0: rows}, tls_context=ctx)


def test_tls_scan_with_trusted_ca(certs):
    _, cert = certs
    with _tls_broker(certs) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", "tls.topic",
            overrides={"security.protocol": "ssl", "ssl.ca.location": cert},
        )
        cfg = AnalyzerConfig(num_partitions=1, batch_size=64)
        m = run_scan("tls.topic", src, CpuExactBackend(cfg, init_now_s=0), 64).metrics
        src.close()
    assert m.overall_count == 200


def test_tls_untrusted_cert_rejected(certs):
    from kafka_topic_analyzer_tpu.io.kafka_codec import KafkaProtocolError

    with _tls_broker(certs) as broker:
        # SSLError is an OSError, so it surfaces through the clean
        # could-not-reach wrapper with the verification failure named.
        with pytest.raises(KafkaProtocolError, match="certificate"):
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", "tls.topic",
                overrides={"security.protocol": "ssl"},  # system CAs only
            )


def test_tls_verification_can_be_disabled(certs):
    with _tls_broker(certs) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", "tls.topic",
            overrides={
                "security.protocol": "ssl",
                "enable.ssl.certificate.verification": "false",
            },
        )
        assert src.partitions() == [0]
        src.close()


def test_tls_broker_survives_failed_handshake(certs):
    from kafka_topic_analyzer_tpu.io.kafka_codec import KafkaProtocolError

    _, cert = certs
    with _tls_broker(certs) as broker:
        # First client fails verification (system CAs only)...
        with pytest.raises(KafkaProtocolError):
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", "tls.topic",
                overrides={"security.protocol": "ssl"},
            )
        # ...and the broker must still serve the next, trusting client.
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", "tls.topic",
            overrides={"security.protocol": "ssl", "ssl.ca.location": cert},
        )
        assert src.partitions() == [0]
        src.close()


def test_unsupported_security_protocol():
    with pytest.raises(ValueError, match="unsupported"):
        KafkaWireSource(
            "127.0.0.1:1", "x", overrides={"security.protocol": "kerberos"}
        )


def test_sasl_ssl_requires_credentials():
    with pytest.raises(ValueError, match="sasl.username"):
        KafkaWireSource(
            "127.0.0.1:1", "x", overrides={"security.protocol": "sasl_ssl"}
        )
