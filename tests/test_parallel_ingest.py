"""Parallel partition-sharded ingest: determinism + fault composition.

The tentpole contract (DESIGN.md §11): for any worker count N, a scan's
`ScanResult` — metrics, degraded/corrupt maps, resume offsets — is
byte-identical to the sequential (N=1) scan of the same topic.  That must
hold not just for clean topics but COMPOSED with the resilience machinery
of earlier PRs: transport faults (`FaultInjector` kills) and deterministic
corruption (`CorruptionInjector` poison) landing inside one worker's
partition group.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    CorruptionConfig,
    IngestConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.parallel.ingest import (
    ParallelIngest,
    shard_partitions,
)

from fake_broker import (
    ChaosTrigger,
    CorruptionInjector,
    FakeBroker,
    FakeCluster,
    FaultInjector,
)

pytestmark = pytest.mark.ingest

TOPIC = "pingest.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 29}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


N_PARTS = 4
N_REC = 300
RECORDS = {p: _mk_records(p, N_REC) for p in range(N_PARTS)}

CFG = AnalyzerConfig(
    num_partitions=N_PARTS, batch_size=128,
    count_alive_keys=True, alive_bitmap_bits=16,
)


def _scan(source, workers=1, batch_size=128):
    backend = CpuExactBackend(CFG, init_now_s=10**10)
    result = run_scan(
        TOPIC, source, backend, batch_size, ingest_workers=workers
    )
    close = getattr(source, "inner", source)
    close.close()
    return result


def _full_doc(result) -> dict:
    """EVERYTHING the determinism contract covers, in one comparable doc:
    metrics, scan window, degraded/corrupt maps."""
    return {
        "metrics": result.metrics.to_dict(
            result.start_offsets, result.end_offsets
        ),
        "start": result.start_offsets,
        "end": result.end_offsets,
        "degraded": result.degraded_partitions,
        "corrupt": result.corrupt_partitions,
    }


# ---------------------------------------------------------------------------
# unit: sharding + sizing


def test_shard_partitions_disjoint_cover():
    parts = [0, 2, 3, 7, 9, 11, 40]
    for n in (1, 2, 3, 4, 7, 9):
        groups = shard_partitions(parts, n)
        assert len(groups) == min(n, len(parts))
        flat = sorted(p for g in groups for p in g)
        assert flat == sorted(parts)  # disjoint cover, nothing dropped
        # Round-robin rule matches the mesh data-shard assignment.
        if n <= len(parts):
            assert groups[0][0] == 0
    with pytest.raises(ValueError):
        shard_partitions(parts, 0)


def test_ingest_config_sizing():
    assert IngestConfig.parse("3").resolve(64) == 3
    assert IngestConfig.parse("8").resolve(4) == 4  # clamp to partitions
    auto = IngestConfig.parse("auto").resolve(10**6)
    # auto sizes from SCHEDULABLE cores (cgroup/affinity aware), one short.
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    assert auto == max(1, cores - 1)
    assert IngestConfig.parse("auto").resolve(2) == 2 or auto == 1
    with pytest.raises(ValueError):
        IngestConfig.parse("0")
    with pytest.raises(ValueError):
        IngestConfig.parse("many")


# ---------------------------------------------------------------------------
# determinism: N workers == 1 worker, byte for byte


@pytest.fixture(scope="module")
def baseline():
    """Sequential (N=1) fake-broker scan — the byte-exact referee."""
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        result = _scan(
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            )
        )
    assert not result.degraded_partitions
    return _full_doc(result)


@pytest.mark.parametrize("workers", [2, 3, 4])
def test_n_workers_byte_identical_to_sequential(baseline, workers):
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        result = _scan(
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            ),
            workers=workers,
        )
    assert result.ingest_workers == workers
    assert _full_doc(result) == baseline


def test_workers_beyond_partitions_clamp(baseline):
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        result = _scan(
            KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            ),
            workers=99,
        )
    assert result.ingest_workers == N_PARTS
    assert _full_doc(result) == baseline


def test_parallel_synthetic_and_staged_backend_deterministic():
    """Cluster-free determinism across worker counts AND the staged
    (prepare-on-worker) path: the TPU backend packs on the ingest workers."""
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend

    spec = SyntheticSpec(
        num_partitions=5, messages_per_partition=1500,
        keys_per_partition=31, seed=3,
    )
    cfg = AnalyzerConfig(
        num_partitions=5, batch_size=256,
        count_alive_keys=True, alive_bitmap_bits=16, enable_hll=True,
    )

    def doc(workers):
        r = run_scan(
            "t", SyntheticSource(spec), TpuBackend(cfg, init_now_s=10**10),
            256, ingest_workers=workers,
        )
        return r.metrics.to_dict(r.start_offsets, r.end_offsets)

    ref = doc(1)
    for n in (2, 3, 5):
        assert doc(n) == ref


# ---------------------------------------------------------------------------
# fault composition: chaos + corruption confined to one worker's partitions


def test_transport_fault_in_one_worker_absorbed(baseline):
    """A connection kill mid-scan (FaultInjector) lands on one worker's
    stream; recovery must keep the N-worker result byte-identical."""
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        trigger = ChaosTrigger(
            src, 2,
            lambda: setattr(
                broker, "faults",
                FaultInjector().drop_connection(100, times=2),
            ),
        )
        result = _scan(trigger, workers=3)
    assert not result.degraded_partitions
    assert _full_doc(result) == baseline


def test_degraded_partition_in_one_worker_matches_sequential():
    """Node 1 dies for good: its partitions degrade inside whichever worker
    owns them, the other workers finish exact, and the whole ScanResult
    (including the degraded map) matches the sequential scan under the
    same fault plan."""

    class _ArmOnFirstFetch(FaultInjector):
        """Inert until armed; arming is one atomic Event.set().  A plain
        ``node.faults = FaultInjector()...`` hand-off from the delay
        callback loses a GIL-preemption race: while the arming thread is
        stalled mid-expression, other handler threads' sends still see
        ``faults is None`` and a whole partition can drain and dodge
        degradation.  Pre-installing the injector and gating it on an
        Event leaves no such window — at worst one already-checked
        in-flight response escapes per connection, which cannot finish a
        multi-fetch partition."""

        def __init__(self):
            super().__init__()
            self.armed = threading.Event()
            self.drop_connection(0, times=10**6)
            self.refuse_connections(times=10**6)

        def take_drop(self):
            return super().take_drop() if self.armed.is_set() else None

        def take_refusal(self):
            return super().take_refusal() if self.armed.is_set() else False

    def run(workers):
        inj = _ArmOnFirstFetch()

        def arm_on_first_fetch(api_key: int, node_id: int) -> float:
            if api_key == kc.API_FETCH and node_id == 1:
                inj.armed.set()
            return 0.0

        with FakeCluster(
            TOPIC, RECORDS, n_nodes=2, max_records_per_fetch=60,
            response_delay=arm_on_first_fetch,
        ) as cluster:
            cluster.nodes[1].faults = inj
            src = KafkaWireSource(
                cluster.bootstrap, TOPIC,
                overrides=dict(
                    FAST_RETRY,
                    **{
                        "transport.retry.budget": "3",
                        "socket.timeout.ms": "500",
                    },
                ),
            )
            return _scan(src, workers=workers)

    seq = run(1)
    par = run(3)
    # Reason strings embed each run's ephemeral broker port, so the
    # cross-run comparison is structural: same partitions, same cause.
    assert seq.degraded_partitions
    assert set(par.degraded_partitions) == set(seq.degraded_partitions)
    for p, reason in par.degraded_partitions.items():
        assert "transport failures" in reason
        assert "transport failures" in seq.degraded_partitions[p]
    # Healthy partitions' rows byte-match; the degraded tail undercounts
    # identically (the kill point is deterministic: first fetch to node 1).
    sdoc, pdoc = _full_doc(seq), _full_doc(par)
    healthy = [
        str(p) for p in range(N_PARTS) if p not in seq.degraded_partitions
    ]
    for p in healthy:
        assert pdoc["metrics"]["partitions"][p] == sdoc["metrics"]["partitions"][p]
    assert pdoc["start"] == sdoc["start"] and pdoc["end"] == sdoc["end"]


def test_corruption_in_one_worker_matches_sequential(tmp_path):
    """Deterministic poison in partition 1's chunks (exactly one worker's
    group under N=3) with --on-corruption=quarantine: metrics, the corrupt
    accounting map, and the quarantine spool all match the sequential scan."""
    def poisoned():
        inj = (
            CorruptionInjector()
            .flip_byte(1, chunk=1, offset=-1)     # crc-mismatch
            .flip_byte(1, chunk=3, offset=-3)     # crc-mismatch
        )
        return FakeBroker(
            TOPIC, RECORDS, max_records_per_fetch=50, corruption=inj,
            honor_partition_max_bytes=True,
        )

    def run(workers, qdir):
        with poisoned() as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC,
                overrides=dict(FAST_RETRY, **{"check.crcs": "true"}),
                corruption=CorruptionConfig(
                    policy="quarantine", quarantine_dir=qdir
                ),
            )
            return _scan(src, workers=workers)

    seq = run(1, str(tmp_path / "q1"))
    par = run(3, str(tmp_path / "q3"))
    assert set(seq.corrupt_partitions) == {1}
    assert _full_doc(par) == _full_doc(seq)
    spooled = sorted(os.listdir(tmp_path / "q3"))
    assert spooled == sorted(os.listdir(tmp_path / "q1"))
    assert len([f for f in spooled if f.endswith(".bin")]) == 2


def test_snapshot_offsets_identical_across_worker_counts(tmp_path):
    """Checkpoints stay fold-consistent per partition: the final snapshot's
    resume offsets (and records_seen) are byte-identical for N=1 and N=3."""
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend

    def snap_meta(workers, d):
        with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            )
            run_scan(
                TOPIC, src, TpuBackend(CFG, init_now_s=10**10), 128,
                snapshot_dir=str(d), snapshot_every_s=0.0,
                ingest_workers=workers,
            )
            src.close()
        with np.load(
            os.path.join(str(d), "scan_snapshot.npz"), allow_pickle=False
        ) as z:
            meta = json.loads(str(z["__meta__"]))
        return meta["next_offsets"], meta["records_seen"]

    assert snap_meta(1, tmp_path / "w1") == snap_meta(3, tmp_path / "w3")


# ---------------------------------------------------------------------------
# composed parallelism: mesh x workers x superbatch in ONE scan (PR-7
# tentpole).  The contract (DESIGN.md §14): for any (mesh, workers, K)
# the ScanResult is byte-identical to the sequential single-device scan.

MATRIX_SPEC = SyntheticSpec(
    num_partitions=5, messages_per_partition=1000,
    keys_per_partition=31, tombstone_permille=120, seed=3,
)
MATRIX_BASE = dict(
    num_partitions=5, batch_size=256,
    count_alive_keys=True, alive_bitmap_bits=16, enable_hll=True, hll_p=10,
)


def _composed_backend(mesh, k):
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import DispatchConfig

    dispatch = DispatchConfig(superbatch=k, depth=2)
    if mesh == 1:
        return TpuBackend(
            AnalyzerConfig(**MATRIX_BASE), init_now_s=10**10,
            dispatch=dispatch,
        )
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    return ShardedTpuBackend(
        AnalyzerConfig(**MATRIX_BASE, mesh_shape=(mesh, 1)),
        init_now_s=10**10, dispatch=dispatch,
    )


@pytest.fixture(scope="module")
def composed_baseline():
    """Sequential single-device scan — the matrix's byte-exact referee."""
    r = run_scan("t", SyntheticSource(MATRIX_SPEC), _composed_backend(1, 1), 256)
    return _full_doc(r)


@pytest.mark.parametrize("k", [1, 4])
@pytest.mark.parametrize("workers", [1, 2, 4])
@pytest.mark.parametrize("mesh", [1, 2, 4])
def test_composed_matrix_byte_identical(composed_baseline, mesh, workers, k):
    if (mesh, workers, k) == (1, 1, 1):
        return  # the referee itself
    import jax

    if mesh > len(jax.devices()):
        pytest.skip("needs more virtual devices")
    r = run_scan(
        "t", SyntheticSource(MATRIX_SPEC), _composed_backend(mesh, k), 256,
        ingest_workers=workers,
    )
    assert r.superbatch_k == k
    assert _full_doc(r) == composed_baseline
    # The resolved per-controller record always covers this process.
    assert r.ingest_workers_per_controller == [r.ingest_workers]
    if mesh == 1:
        assert r.ingest_workers == min(workers, 5)
    else:
        # Sharded: every fed row needs >= 1 stream, extras go to the rows
        # with the most partitions — never more than one per partition.
        assert min(mesh, 5) <= r.ingest_workers <= 5


def _sharded_wire_backend(k=1):
    """A (2, 1) sharded-mesh backend for the wire tests below: 4
    partitions split rows [0, 2] / [1, 3], so ingest_workers=4 gives each
    row a 2-worker fan-in (the per-controller composition under test)."""
    from kafka_topic_analyzer_tpu.config import DispatchConfig
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg = AnalyzerConfig(
        num_partitions=N_PARTS, batch_size=128,
        count_alive_keys=True, alive_bitmap_bits=16, mesh_shape=(2, 1),
    )
    return ShardedTpuBackend(
        cfg, init_now_s=10**10, dispatch=DispatchConfig(superbatch=k, depth=2)
    )


def test_composed_fault_in_one_worker_absorbed():
    """A transport kill lands inside ONE worker's stream of ONE data
    row's fan-in (mesh 2 x workers 4 x K 2); retry + recovery must keep
    the composed result byte-identical to the sequential sharded scan."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")

    def run(workers, chaos, k=1):
        with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            )
            feed = src
            if chaos:
                feed = ChaosTrigger(
                    src, 2,
                    lambda: setattr(
                        broker, "faults",
                        FaultInjector().drop_connection(100, times=2),
                    ),
                )
            result = run_scan(
                TOPIC, feed, _sharded_wire_backend(k=k), 128,
                ingest_workers=workers,
            )
            src.close()
        return result

    ref = run(1, chaos=False)
    assert not ref.degraded_partitions
    faulted = run(4, chaos=True, k=2)
    assert not faulted.degraded_partitions
    assert faulted.ingest_workers == 4
    assert _full_doc(faulted) == _full_doc(ref)


def test_composed_corruption_in_one_worker_matches_sequential(tmp_path):
    """Deterministic poison in partition 1 — exactly one worker's group of
    one row's fan-in under mesh 2 x workers 4 — with quarantine: metrics,
    corrupt accounting, and the spool all match the sequential sharded
    scan."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")

    def poisoned():
        inj = (
            CorruptionInjector()
            .flip_byte(1, chunk=1, offset=-1)
            .flip_byte(1, chunk=3, offset=-3)
        )
        return FakeBroker(
            TOPIC, RECORDS, max_records_per_fetch=50, corruption=inj,
            honor_partition_max_bytes=True,
        )

    def run(workers, qdir, k=1):
        with poisoned() as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC,
                overrides=dict(FAST_RETRY, **{"check.crcs": "true"}),
                corruption=CorruptionConfig(
                    policy="quarantine", quarantine_dir=qdir
                ),
            )
            result = run_scan(
                TOPIC, src, _sharded_wire_backend(k=k), 128,
                ingest_workers=workers,
            )
            src.close()
        return result

    seq = run(1, str(tmp_path / "q1"))
    par = run(4, str(tmp_path / "q4"), k=2)
    assert set(seq.corrupt_partitions) == {1}
    assert _full_doc(par) == _full_doc(seq)
    assert sorted(os.listdir(tmp_path / "q4")) == sorted(
        os.listdir(tmp_path / "q1")
    )


def test_allocate_row_workers_deterministic():
    from kafka_topic_analyzer_tpu.parallel.ingest import allocate_row_workers

    # Floor: every non-empty row gets a stream even under a tiny budget.
    assert allocate_row_workers(1, {0: 3, 1: 2}) == {0: 1, 1: 1}
    # Extras chase the highest partitions-per-worker ratio, ties by row.
    assert allocate_row_workers(4, {0: 3, 1: 2}) == {0: 2, 1: 2}
    assert allocate_row_workers(5, {0: 3, 1: 2}) == {0: 3, 1: 2}
    # Clamped at the row's partition count; empty rows get nothing.
    assert allocate_row_workers(99, {0: 3, 1: 0, 2: 1}) == {0: 3, 1: 0, 2: 1}
    with pytest.raises(ValueError):
        allocate_row_workers(0, {0: 1})


# ---------------------------------------------------------------------------
# pool mechanics: error propagation, close-on-exit, metrics


class _Boom(Exception):
    pass


class _ExplodingSource(SyntheticSource):
    """batches() dies after 2 batches — but only the stream owning
    ``bad_partition``; other workers' streams run clean."""

    def __init__(self, spec, bad_partition):
        super().__init__(spec)
        self.bad = bad_partition

    def batches(self, batch_size, partitions=None, start_at=None):
        it = super().batches(batch_size, partitions, start_at)
        if partitions is None or self.bad not in partitions:
            yield from it
            return
        for i, b in enumerate(it):
            if i >= 2:
                raise _Boom()
            yield b


def test_worker_error_aborts_scan_without_leaks():
    spec = SyntheticSpec(num_partitions=4, messages_per_partition=4000)
    cfg = AnalyzerConfig(num_partitions=4, batch_size=128)
    before = threading.active_count()
    with pytest.raises(_Boom):
        run_scan(
            "t", _ExplodingSource(spec, bad_partition=1),
            CpuExactBackend(cfg, init_now_s=0), 128, ingest_workers=3,
        )
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_pool_close_cancels_and_closes_streams():
    """Abandoning the fan-in mid-stream closes every worker's underlying
    generator (GeneratorExit), not just the threads."""
    spec = SyntheticSpec(num_partitions=3, messages_per_partition=5000)
    closed = []

    class Tracking(SyntheticSource):
        def batches(self, batch_size, partitions=None, start_at=None):
            try:
                yield from super().batches(batch_size, partitions, start_at)
            finally:
                closed.append(tuple(partitions or ()))

    pool = ParallelIngest(
        Tracking(spec), 64, shard_partitions([0, 1, 2], 3), depth=2
    )
    next(iter(pool))
    pool.close()
    pool.close()  # idempotent
    assert len(closed) == 3


def test_per_worker_telemetry_recorded():
    from kafka_topic_analyzer_tpu.results import IngestStats

    spec = SyntheticSpec(num_partitions=4, messages_per_partition=1000)
    cfg = AnalyzerConfig(num_partitions=4, batch_size=256)
    result = run_scan(
        "t", SyntheticSource(spec), CpuExactBackend(cfg, init_now_s=0),
        256, ingest_workers=2,
    )
    stats = IngestStats.from_telemetry(result.telemetry)
    assert set(stats.workers) >= {"0", "1"}
    assert sum(stats.workers.values()) >= 4000  # cumulative registry


# ---------------------------------------------------------------------------
# CLI surface


@pytest.mark.parametrize("mesh", ["2", "1,2"])
def test_cli_workers_compose_with_sharded_mesh(capsys, mesh):
    """--ingest-workers composes with --mesh (the PR-7 tentpole): the
    sharded scan runs a per-controller fan-in and the --json report
    records the resolved per-controller counts."""
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "t", "--source", "synthetic",
        "--synthetic", "partitions=4,messages=2000",
        "--mesh", mesh, "--backend", "tpu",
        "--ingest-workers", "2", "--json", "--quiet",
    ])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert doc["ingest_workers"] == 2
    assert doc["ingest_workers_per_controller"] == [2]


def test_cli_workers_resolve_passthrough_under_mesh():
    """Under a sharded mesh the CLI hands the PARSED IngestConfig to the
    engine unresolved: per-controller resolution needs each controller's
    shard partition count (and its own core count for 'auto'), which the
    CLI cannot know for remote hosts."""
    from kafka_topic_analyzer_tpu.cli import build_parser, resolve_ingest_workers
    from kafka_topic_analyzer_tpu.config import IngestConfig

    args = build_parser().parse_args(
        ["-t", "t", "--ingest-workers", "auto"]
    )
    assert resolve_ingest_workers(args, (2, 1), 64) == IngestConfig("auto")
    assert resolve_ingest_workers(args, (1, 2), 64) == IngestConfig("auto")
    assert resolve_ingest_workers(args, (1, 1), 64) >= 1
    args = build_parser().parse_args(["-t", "t", "--ingest-workers", "3"])
    assert resolve_ingest_workers(args, (4, 1), 64) == IngestConfig(3)
    assert resolve_ingest_workers(args, (1, 1), 64) == 3


def test_cli_rejects_bad_worker_spec(capsys):
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "t", "--source", "synthetic",
        "--synthetic", "partitions=4,messages=100",
        "--ingest-workers", "lots", "--quiet",
    ])
    assert rc == 1
    assert "--ingest-workers" in capsys.readouterr().err


def test_cli_stats_and_json_report_worker_count(capsys):
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "t", "--source", "synthetic",
        "--synthetic", "partitions=4,messages=2000",
        "--ingest-workers", "3", "--stats", "--json", "--quiet",
    ])
    assert rc == 0
    out = capsys.readouterr()
    doc = json.loads(out.out.splitlines()[-1])
    assert doc["ingest_workers"] == 3
    assert "ingest: 3 worker(s)" in out.err
