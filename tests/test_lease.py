"""Lease-based multi-instance fleets (ISSUE 16): ownership, fencing, failover.

The contract under test, per DESIGN.md §23:

- STORES: both lease stores implement the same CAS-shaped contract —
  FileLeaseStore (lock + atomic rename + read-back verify) and
  ObjectLeaseStore (ETag-fenced conditional PUTs, with the ambiguous
  retried-PUT 412 resolved by read-back);
- EPOCH RULES: absent record → 1; released/expired/self-owned → +1;
  live held-elsewhere → refused.  Epochs only ever grow — released
  records are kept, never deleted;
- FAILOVER: a killed instance leaves its leases dangling; a peer takes
  over at expiry (booked as takeover + kta_fleet_failovers_total),
  resumes from the dead instance's checkpoint, and the final per-topic
  metrics are byte-identical to a solo scan — no loss, no double-count;
- FENCING: a paused zombie's late checkpoint write is refused with the
  named StaleLeaseEpochError, the topic goes "fenced" (not "failed"),
  and the loss is booked on kta_lease_losses_total;
- DEGRADATION: a store outage during renewal defers (books "deferred")
  and the lease survives until local expiry — never an early self-fence;
- SHUTDOWN: SIGTERM releases every held lease after the final
  checkpoint pass, so a rolling restart fails over immediately.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import pytest

from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.checkpoint import (
    StaleLeaseEpochError,
    list_topic_snapshots,
    load_snapshot,
    save_snapshot,
    snapshot_info,
    topic_snapshot_dir,
)
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    DispatchConfig,
    FollowConfig,
    HealthConfig,
    LeaseConfig,
    SegmentFetchConfig,
    TransportRetryConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.fleet.lease import (
    FileLeaseStore,
    Lease,
    LeaseManager,
    ObjectLeaseStore,
)
from kafka_topic_analyzer_tpu.fleet.scheduler import FleetScheduler, TopicSeed
from kafka_topic_analyzer_tpu.fleet.service import FleetService
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.io.objstore import RetryingHttp
from kafka_topic_analyzer_tpu.io.retry import Backoff
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs.health import HealthEngine, built_in_rules

from fake_broker import FakeBroker
from fake_objstore import FakeObjectStore

pytestmark = pytest.mark.lease

TOPICS = ["lease.a", "lease.b"]
N_PARTS = 2
PHASE1_N = 96
PHASE2_N = 48
FULL_N = PHASE1_N + PHASE2_N

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}

FAST_FOLLOW = dict(
    poll_interval_s=0.02,
    idle_backoff_max_s=0.05,
)


class _Clock:
    """The shared fake WALL clock lease expiry runs on (the follow
    loop's pass clock stays real/monotonic — leases only care about
    the store-visible expiry time)."""

    def __init__(self, t: float = 1_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _mk_records(salt: int, partition: int, lo: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{salt}-{partition}-{i % 13}".encode() if i % 5 else None,
            bytes(11 + ((i + salt) % 7)) if i % 7 else None,
        )
        for i in range(lo, lo + n)
    ]


def _topic_records(salt: int, n: int, lo: int = 0):
    return {p: _mk_records(salt, p, lo, n) for p in range(N_PARTS)}


def _mk_broker(records_by_topic, **kw):
    names = list(records_by_topic)
    return FakeBroker(
        names[0],
        records_by_topic[names[0]],
        extra_topics={t: records_by_topic[t] for t in names[1:]},
        max_records_per_fetch=48,
        **kw,
    )


def _cfg(parts=N_PARTS) -> AnalyzerConfig:
    return AnalyzerConfig(
        num_partitions=parts,
        batch_size=64,
        count_alive_keys=True,
        alive_bitmap_bits=16,
    )


def _source(broker, topic):
    return KafkaWireSource(
        f"127.0.0.1:{broker.port}", topic, overrides=dict(FAST_RETRY)
    )


def _metrics_doc(result) -> dict:
    return result.metrics.to_dict(result.start_offsets, result.end_offsets)


def _fleet_service(
    broker,
    topics=TOPICS,
    *,
    leases=None,
    instance="solo",
    follow=None,
    snapshot_dir=None,
    resume=False,
    max_concurrent=3,
):
    scheduler = FleetScheduler(3, 3, max_concurrent, instance=instance)

    def source_factory(topic):
        return _source(broker, topic)

    def backend_factory(topic, parts, grant):
        return TpuBackend(
            _cfg(parts),
            dispatch=DispatchConfig(
                superbatch=1, depth=grant.dispatch_depth
            ),
            init_now_s=10**10,
        )

    seeds = [TopicSeed(name=t, partitions=N_PARTS) for t in topics]
    return FleetService(
        seeds, source_factory, backend_factory, 64, scheduler,
        follow=follow, snapshot_dir=snapshot_dir, resume=resume,
        leases=leases, instance=instance,
    )


def _wait_for(predicate, timeout_s=30.0, interval_s=0.01, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


def _acq(outcome: str, instance: str) -> float:
    return obs_metrics.LEASE_ACQUISITIONS.labels(
        outcome=outcome, instance=instance
    ).value


def _renewals(outcome: str, instance: str) -> float:
    return obs_metrics.LEASE_RENEWALS.labels(
        outcome=outcome, instance=instance
    ).value


def _losses(instance: str) -> float:
    return obs_metrics.LEASE_LOSSES.labels(instance=instance).value


def _failovers(instance: str) -> float:
    return obs_metrics.FLEET_FAILOVERS.labels(instance=instance).value


def _held_gauge(topic: str, instance: str) -> float:
    return obs_metrics.LEASE_HELD.labels(
        topic=topic, instance=instance
    ).value


def _fetch_cfg() -> SegmentFetchConfig:
    return SegmentFetchConfig(
        retry=TransportRetryConfig(
            backoff_ms=1, backoff_max_ms=2, retry_budget=4, jitter=0.0
        ),
        timeout_s=5.0,
    )


def _obj_store(server) -> ObjectLeaseStore:
    return ObjectLeaseStore(RetryingHttp(server.url, _fetch_cfg()))


# ---------------------------------------------------------------------------
# lease records


def test_lease_record_round_trip():
    lease = Lease(
        topic="t", owner="i-1", epoch=3, expires_at=12.5, acquired_at=2.5
    )
    assert Lease.from_json(lease.to_json()) == lease
    released = Lease(
        topic="t", owner=None, epoch=3, expires_at=12.5, acquired_at=2.5
    )
    assert Lease.from_json(released.to_json()).owner is None


# ---------------------------------------------------------------------------
# FileLeaseStore


def test_file_store_round_trip_and_owners(tmp_path):
    store = FileLeaseStore(str(tmp_path))
    assert store.read("t") == (None, None)
    lease = Lease(
        topic="t", owner="A", epoch=1, expires_at=10.0, acquired_at=0.0
    )
    token = store.write("t", lease, None)
    assert token is not None
    got, tok = store.read("t")
    assert got == lease and tok is not None
    assert store.owners() == {"A"}
    released = Lease(
        topic="t", owner=None, epoch=1, expires_at=0.0, acquired_at=0.0
    )
    assert store.write("t", released, token) is not None
    assert store.owners() == set()  # released records name no owner


def test_file_store_lost_race_detected_by_read_back(tmp_path):
    """The verify seam: a competing write landing between the rename and
    the read-back must turn OUR write into a reported lost race."""
    racer = FileLeaseStore(str(tmp_path))

    def competing_write(topic):
        # Bypass the lock (our writer holds it): model a racer whose
        # rename lands between our replace and our read-back.
        theirs = Lease(
            topic=topic, owner="B", epoch=9,
            expires_at=99.0, acquired_at=0.0,
        )
        with open(racer._path(topic), "wb") as f:
            f.write(theirs.to_json())

    store = FileLeaseStore(str(tmp_path), verify_hook=competing_write)
    mine = Lease(
        topic="t", owner="A", epoch=1, expires_at=10.0, acquired_at=0.0
    )
    assert store.write("t", mine, None) is None  # racer overwrote us
    got, _tok = store.read("t")
    assert got.owner == "B" and got.epoch == 9


def test_file_store_lock_contention(tmp_path):
    store = FileLeaseStore(str(tmp_path))
    lease = Lease(
        topic="t", owner="A", epoch=1, expires_at=10.0, acquired_at=0.0
    )
    lock = store._path("t") + ".lock"
    # A LIVE lock (a concurrent writer inside the section) = lost race.
    with open(lock, "w"):
        pass
    assert store.write("t", lease, None) is None
    # A STALE lock (a crashed writer's leavings) is broken and the
    # write proceeds.
    old = time.time() - FileLeaseStore.LOCK_STALE_S - 1.0
    os.utime(lock, (old, old))
    assert store.write("t", lease, None) is not None
    assert not os.path.exists(lock)


def test_file_store_corrupt_record_carries_a_cas_token(tmp_path):
    """A corrupt record reads as absent but its token still names the
    bytes on disk: a write with that token overwrites the wreck, while
    an expect-absent write (token None) fails the CAS — the topic never
    becomes permanently unacquirable."""
    store = FileLeaseStore(str(tmp_path))
    with open(store._path("t"), "wb") as f:
        f.write(b"{not json")
    got, token = store.read("t")
    assert got is None and token is not None
    lease = Lease(
        topic="t", owner="A", epoch=1, expires_at=10.0, acquired_at=0.0
    )
    assert store.write("t", lease, None) is None  # expect-absent: refused
    assert store.write("t", lease, token) is not None
    rec, _ = store.read("t")
    assert rec == lease


def test_file_store_concurrent_acquire_grants_exactly_one(tmp_path):
    """The read->decide->write race the CAS exists for: two instances
    both read the SAME absent record (the barrier forces the
    interleaving) and then write — serialized through the lock or not,
    exactly ONE may be granted epoch 1.  Without the in-lock compare
    both writes would succeed and two owners would hold the same epoch,
    a split-brain the checkpoint fence cannot catch."""
    barrier = threading.Barrier(2)

    class BarrierStore(FileLeaseStore):
        def read(self, topic):
            out = super().read(topic)
            barrier.wait(timeout=10)
            return out

    clock = _Clock()
    mgrs = {
        name: LeaseManager(
            BarrierStore(str(tmp_path)), name, ttl_s=30.0, clock=clock
        )
        for name in ("A", "B")
    }
    got = {}

    def race(name):
        try:
            got[name] = mgrs[name].acquire("t")
        except BaseException as e:  # noqa: BLE001 — surfaced below
            got[name] = e

    threads = [
        threading.Thread(target=race, args=(n,)) for n in mgrs
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not any(isinstance(v, BaseException) for v in got.values()), got
    grants = {n for n, e in got.items() if e is not None}
    assert len(grants) == 1, f"double grant: {got}"
    winner = grants.pop()
    loser = ({"A", "B"} - {winner}).pop()
    assert got[winner] == 1
    assert mgrs[winner].is_held("t") and not mgrs[loser].is_held("t")
    rec, _ = FileLeaseStore(str(tmp_path)).read("t")
    assert rec.owner == winner and rec.epoch == 1


# ---------------------------------------------------------------------------
# conditional PUTs + ObjectLeaseStore


def test_put_conditional_requires_exactly_one_condition():
    http = RetryingHttp("http://127.0.0.1:9/bucket", _fetch_cfg())
    with pytest.raises(ValueError, match="exactly one"):
        http.put_conditional("/bucket/k", b"x")
    with pytest.raises(ValueError, match="exactly one"):
        http.put_conditional(
            "/bucket/k", b"x", if_match="e", if_none_match=True
        )


def test_object_store_create_replace_and_stale_etag(tmp_path):
    with FakeObjectStore({}) as server:
        store = _obj_store(server)
        a1 = Lease(
            topic="t", owner="A", epoch=1, expires_at=10.0, acquired_at=0.0
        )
        token = store.write("t", a1, None)  # If-None-Match: * create
        assert token
        got, tok = store.read("t")
        assert got == a1 and tok == token
        # If-Match replace with the read token succeeds.
        a1r = Lease(
            topic="t", owner="A", epoch=1, expires_at=20.0, acquired_at=0.0
        )
        token2 = store.write("t", a1r, token)
        assert token2 and token2 != token
        # A competitor's record lands; our now-stale token is refused
        # and the read-back shows a different owner → lost race.
        b2 = Lease(
            topic="t", owner="B", epoch=2, expires_at=30.0, acquired_at=0.0
        )
        server.root["_kta_leases/t.json"] = b2.to_json()
        a1rr = Lease(
            topic="t", owner="A", epoch=1, expires_at=40.0, acquired_at=0.0
        )
        assert store.write("t", a1rr, token2) is None


def test_object_store_ambiguous_put_resolved_by_read_back():
    """The lost-response PUT: applied server-side, connection dropped
    before the response.  The transport retry 412s against our OWN
    write; the store must recognize it and report success."""
    with FakeObjectStore({}) as server:
        store = _obj_store(server)
        a1 = Lease(
            topic="t", owner="A", epoch=1, expires_at=10.0, acquired_at=0.0
        )
        token = store.write("t", a1, None)
        server.script_put("_kta_leases/t.json", "lost")
        renewal = Lease(
            topic="t", owner="A", epoch=1, expires_at=20.0, acquired_at=0.0
        )
        new_token = store.write("t", renewal, token)
        assert new_token is not None  # our own write fenced us: resolved
        got, _ = store.read("t")
        assert got == renewal
        assert server.puts["_kta_leases/t.json"] >= 2  # it DID retry


def test_object_store_race_loses_acquire():
    """A competing writer winning the CAS race mid-PUT is a genuine 412:
    the manager books lost-race and does not hold."""
    with FakeObjectStore({}) as server:
        store = _obj_store(server)
        clock = _Clock()
        competitor = Lease(
            topic="t", owner="B", epoch=1,
            expires_at=clock() + 60.0, acquired_at=clock(),
        )
        server.script_put(
            "_kta_leases/t.json", ("race", competitor.to_json())
        )
        mgr = LeaseManager(store, "A", ttl_s=30.0, clock=clock)
        lost0 = _acq("lost-race", "A")
        assert mgr.acquire("t") is None
        assert _acq("lost-race", "A") - lost0 == 1
        assert not mgr.is_held("t")
        got, _ = store.read("t")
        assert got.owner == "B"


def test_object_store_transient_5xx_retried():
    with FakeObjectStore({}) as server:
        store = _obj_store(server)
        server.script_put("_kta_leases/t.json", ("status", 503))
        lease = Lease(
            topic="t", owner="A", epoch=1, expires_at=10.0, acquired_at=0.0
        )
        assert store.write("t", lease, None) is not None
        assert server.puts["_kta_leases/t.json"] == 2


def test_object_store_corrupt_record_is_recoverable():
    """A corrupt lease object reads as absent but keeps its ETag as the
    token, so the next acquire If-Match-overwrites the wreck instead of
    If-None-Match-creating against it (a 412 loop that would leave the
    topic permanently unacquirable)."""
    with FakeObjectStore({}) as server:
        store = _obj_store(server)
        server.root["_kta_leases/t.json"] = b"{not json"
        got, token = store.read("t")
        assert got is None and token is not None  # the wreck's ETag
        clock = _Clock()
        mgr = LeaseManager(store, "A", ttl_s=30.0, clock=clock)
        assert mgr.acquire("t") == 1  # epoch restarts: history is gone
        rec, _ = store.read("t")
        assert rec is not None and rec.owner == "A" and rec.epoch == 1


def test_object_store_clock_skew_expires_lease_early():
    """A writer whose clock runs behind persists an already-stale
    expiry: a peer sees the record expired and takes over (failover)."""
    with FakeObjectStore({}) as server:
        store = _obj_store(server)
        clock = _Clock()
        server.script_put("_kta_leases/t.json", ("skew", -100.0))
        mgr_a = LeaseManager(store, "A", ttl_s=30.0, clock=clock)
        assert mgr_a.acquire("t") == 1
        got, _ = store.read("t")
        assert got.expires_at <= clock()  # skewed into the past
        fo0 = _failovers("B")
        mgr_b = LeaseManager(store, "B", ttl_s=30.0, clock=clock)
        assert mgr_b.acquire("t") == 2  # takeover without waiting a TTL
        assert _failovers("B") - fo0 == 1


# ---------------------------------------------------------------------------
# LeaseManager epoch rules


def test_acquire_epoch_rules_and_release(tmp_path):
    store = FileLeaseStore(str(tmp_path))
    clock = _Clock()
    a = LeaseManager(store, "A", ttl_s=30.0, clock=clock)
    b = LeaseManager(store, "B", ttl_s=30.0, clock=clock)
    acq0 = _acq("acquired", "A")
    assert a.acquire("t") == 1
    assert a.acquire("t") == 1  # idempotent while held
    assert _acq("acquired", "A") - acq0 == 1
    assert a.is_held("t") and a.epoch("t") == 1
    assert _held_gauge("t", "A") == 1
    # Held elsewhere, unexpired → refused and booked.
    he0 = _acq("held-elsewhere", "B")
    assert b.acquire("t") is None
    assert _acq("held-elsewhere", "B") - he0 == 1
    # Clean release keeps the record (owner None, SAME epoch).
    rel0 = _acq("released", "A")
    a.release("t")
    assert not a.is_held("t") and _held_gauge("t", "A") == 0
    assert _acq("released", "A") - rel0 == 1
    rec, _ = store.read("t")
    assert rec.owner is None and rec.epoch == 1
    # The successor bumps the epoch past every record ever written.
    assert b.acquire("t") == 2
    assert sorted(a.known_instances()) == ["A", "B"]
    assert b.held_topics() == ["t"]


def test_expired_lease_takeover_and_zombie_fencing(tmp_path):
    store = FileLeaseStore(str(tmp_path))
    clock = _Clock()
    a = LeaseManager(store, "A", ttl_s=5.0, clock=clock)
    b = LeaseManager(store, "B", ttl_s=5.0, clock=clock)
    assert a.acquire("t") == 1
    clock.advance(6.0)  # A's lease expires un-renewed
    take0, fo0 = _acq("takeover", "B"), _failovers("B")
    assert b.acquire("t") == 2
    assert _acq("takeover", "B") - take0 == 1
    assert _failovers("B") - fo0 == 1
    # The zombie still believes it holds epoch 1; its renewal observes
    # the successor and self-fences — booked as a loss, never a write
    # over B's record.
    loss0 = _losses("A")
    assert a.is_held("t")  # stale local view, by design
    assert a.renew("t") is False
    assert _losses("A") - loss0 == 1
    assert not a.is_held("t")
    rec, _ = store.read("t")
    assert rec.owner == "B" and rec.epoch == 2  # untouched by the zombie


def test_renewal_outage_defers_until_local_expiry(tmp_path):
    class FlakyStore(FileLeaseStore):
        def __init__(self, directory):
            super().__init__(directory)
            self.fail_writes = False

        def write(self, topic, lease, token):
            if self.fail_writes:
                raise OSError("injected store outage")
            return super().write(topic, lease, token)

    store = FlakyStore(str(tmp_path))
    clock = _Clock()
    backoff = Backoff(
        TransportRetryConfig(backoff_ms=1, backoff_max_ms=2, jitter=0.0),
        sleep=lambda s: None,
    )
    mgr = LeaseManager(
        store, "A", ttl_s=10.0, clock=clock, backoff=backoff,
        renew_attempts=2,
    )
    assert mgr.acquire("t") == 1
    store.fail_writes = True
    # Outage inside the TTL: deferred, still held, NO self-fence.
    d0 = _renewals("deferred", "A")
    clock.advance(3.0)
    assert mgr.renew("t") is True
    assert _renewals("deferred", "A") - d0 == 1
    assert mgr.is_held("t")
    # Store heals before expiry: the next renewal extends normally.
    store.fail_writes = False
    r0 = _renewals("renewed", "A")
    assert mgr.renew("t") is True
    assert _renewals("renewed", "A") - r0 == 1
    # Outage outlasting the TTL: the lease dies at local expiry.
    store.fail_writes = True
    clock.advance(11.0)
    loss0 = _losses("A")
    assert mgr.renew("t") is False
    assert _losses("A") - loss0 == 1
    assert not mgr.is_held("t")


# ---------------------------------------------------------------------------
# checkpoint epoch fencing (the named error)


def test_checkpoint_epoch_fence_refuses_stale_saves_and_loads(tmp_path):
    topic = "lease.f"
    d = str(tmp_path / "snap")
    records = {topic: {0: _mk_records(3, 0, 0, 40)}}
    with _mk_broker(records) as broker:
        src = _source(broker, topic)
        backend = TpuBackend(_cfg(1), init_now_s=10**10)
        res = run_scan(
            topic, src, backend, 64,
            snapshot_dir=d, final_snapshot=True, lease_epoch=2,
        )
        src.close()
    assert snapshot_info(d)["lease_epoch"] == 2
    # A stale writer (fenced zombie) is refused with the NAMED error.
    with pytest.raises(StaleLeaseEpochError, match="STALE-LEASE-EPOCH"):
        save_snapshot(
            d, topic, backend.config, backend.get_state(),
            res.next_offsets, int(res.metrics.overall_count),
            backend.init_now_s, lease_epoch=1,
        )
    # A stale loader is refused too — resuming over a successor's state
    # would double-count.
    with pytest.raises(StaleLeaseEpochError, match="STALE-LEASE-EPOCH"):
        load_snapshot(d, topic, backend.config, lease_epoch=1)
    # The successor (newer epoch) resumes the predecessor's checkpoint:
    # that IS the failover path.
    assert load_snapshot(d, topic, backend.config, lease_epoch=3) is not None
    # Epoch-less solo scans are untouched by the fence.
    assert load_snapshot(d, topic, backend.config) is not None


# ---------------------------------------------------------------------------
# health rules


def test_lease_alert_rules_fire_and_resolve():
    clock = {"t": 0.0}
    cfg = HealthConfig(
        eval_interval_s=0.001, storm_window_s=2.0, resolve_s=1.0
    )
    eng = HealthEngine(
        built_in_rules(cfg), cfg=cfg, clock=lambda: clock["t"]
    )

    def snap(losses, failovers):
        return {
            "kta_lease_losses_total": {
                "type": "counter",
                "samples": [{"labels": {}, "value": losses}],
            },
            "kta_fleet_failovers_total": {
                "type": "counter",
                "samples": [{"labels": {}, "value": failovers}],
            },
        }

    eng.evaluate(snap(0, 0))
    clock["t"] = 1.0
    doc = eng.evaluate(snap(2, 1))
    firing = {r["rule"]: r for r in doc["firing"]}
    assert "lease_lost" in firing and "failover" in firing
    assert firing["lease_lost"]["evidence"]["lease_losses"] == 2
    assert firing["failover"]["evidence"]["failovers"] == 1
    # Counters stable past the window + resolve time → healthy again.
    for t in (4.0, 5.5, 7.0):
        clock["t"] = t
        doc = eng.evaluate(snap(2, 1))
    assert doc["healthy"]


# ---------------------------------------------------------------------------
# CLI wiring


def test_lease_config_validation_and_store_selection(tmp_path):
    from kafka_topic_analyzer_tpu import cli

    assert not LeaseConfig().enabled
    assert LeaseConfig(instance_id="i-1").enabled
    with pytest.raises(ValueError):
        LeaseConfig(instance_id="i", ttl_s=0.0)
    with pytest.raises(ValueError):
        LeaseConfig(instance_id="i", store="zookeeper")

    cfg = LeaseConfig(instance_id="i-1", ttl_s=5.0)
    mgr = cli.make_lease_manager(cfg, snapshot_dir=str(tmp_path))
    assert isinstance(mgr.store, FileLeaseStore)
    assert mgr.instance == "i-1" and mgr.ttl_s == 5.0
    # auto picks the object store exactly when the segment spec is remote.
    mgr2 = cli.make_lease_manager(
        cfg, store_spec="http://127.0.0.1:9/bucket"
    )
    assert isinstance(mgr2.store, ObjectLeaseStore)
    with pytest.raises(ValueError):  # object leases need a remote spec
        cli.make_lease_manager(
            LeaseConfig(instance_id="i", store="object"),
            snapshot_dir=str(tmp_path), store_spec="./segments",
        )
    with pytest.raises(ValueError):  # file leases need a checkpoint dir
        cli.make_lease_manager(LeaseConfig(instance_id="i", store="file"))


def test_instance_id_without_fleet_is_rejected(capsys):
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main(
        [
            "-t", "t", "--source", "synthetic",
            "--synthetic", "partitions=1,messages=4",
            "--backend", "cpu", "--native", "off", "--quiet",
            "--instance-id", "i-1",
        ]
    )
    assert rc == 1
    err = capsys.readouterr().err
    assert "--instance-id" in err and "--fleet" in err


# ---------------------------------------------------------------------------
# federation: only the lease holder reports a topic's lag


def test_topic_lag_gauge_only_counts_the_holder(tmp_path):
    """Every instance polls every topic (that is how lag is discovered
    before acquiring), but kta_fleet_topic_lag_records merges by SUM
    across the fleet — a non-holder must pin 0 or a federated scrape
    counts each topic's lag once per instance.  The returned lag stays
    real either way: admission needs it to decide whether to acquire."""
    topic = "lease.lag"
    records = {topic: _topic_records(1, 24)}
    with _mk_broker(records) as broker:
        store = FileLeaseStore(str(tmp_path))
        clock = _Clock()
        mgr_b = LeaseManager(store, "B", ttl_s=60.0, clock=clock)
        assert mgr_b.acquire(topic) == 1
        mgr_a = LeaseManager(store, "A", ttl_s=60.0, clock=clock)
        svc = _fleet_service(
            broker, topics=[topic], leases=mgr_a, instance="A"
        )
        scan = svc.scans[topic]
        lag = svc._poll_topic(scan)
        assert lag == N_PARTS * 24  # the poll still measures real lag
        assert (
            obs_metrics.FLEET_TOPIC_LAG.labels(
                topic=topic, instance="A"
            ).value
            == 0
        )  # ... but B owns the topic, so A's gauge reports none of it
        mgr_b.release(topic)
        assert mgr_a.acquire(topic) == 2
        svc._poll_topic(scan)
        assert (
            obs_metrics.FLEET_TOPIC_LAG.labels(
                topic=topic, instance="A"
            ).value
            == lag
        )


# ---------------------------------------------------------------------------
# two-instance chaos: crash failover, byte-identical resumed rollup


def test_two_instance_crash_failover_byte_identity(tmp_path):
    snap = str(tmp_path / "snaps")
    clock = _Clock()
    follow = FollowConfig(**dict(FAST_FOLLOW, checkpoint_every_s=0.0))
    full = {t: _topic_records(i, FULL_N) for i, t in enumerate(TOPICS)}

    referee = {}
    with _mk_broker(full) as broker:
        for t in TOPICS:
            src = _source(broker, t)
            res = run_scan(
                t, src, TpuBackend(_cfg(), init_now_s=10**10), 64
            )
            src.close()
            referee[t] = _metrics_doc(res)

    take0 = _acq("takeover", "B")
    fo0 = _failovers("B")
    phase1 = {t: _topic_records(i, PHASE1_N) for i, t in enumerate(TOPICS)}
    # The response delay widens the window between lease acquisition and
    # pass completion so kill() deterministically lands mid-pass.
    with _mk_broker(
        phase1, response_delay=lambda *_: 0.03
    ) as broker:
        mgr_a = LeaseManager(
            FileLeaseStore(snap), "A", ttl_s=5.0, clock=clock
        )
        svc_a = _fleet_service(
            broker, leases=mgr_a, instance="A",
            follow=follow, snapshot_dir=snap,
        )
        th = threading.Thread(target=svc_a.run_follow)
        th.start()
        _wait_for(
            lambda: set(mgr_a.held_topics()) == set(TOPICS),
            what="instance A to hold every topic lease",
        )
        svc_a.kill()
        th.join(timeout=60)
        assert not th.is_alive()
        # The crash left every lease dangling — still owned by A.
        assert FileLeaseStore(snap).owners() == {"A"}
        # ... but A's in-flight pass committed its checkpoint first.
        inv = list_topic_snapshots(snap)
        assert set(inv) == set(TOPICS)
        assert all(
            info["records_seen"] == N_PARTS * PHASE1_N
            for info in inv.values()
        )

        # One TTL later the records are expired; B takes over, resumes
        # A's checkpoints, and tails the phase-2 records.
        clock.advance(5.0 + 1.0)
        broker.response_delay = None
        for i, t in enumerate(TOPICS):
            for p, recs in _topic_records(
                i, PHASE2_N, lo=PHASE1_N
            ).items():
                broker.produce(p, recs, topic=t)
        mgr_b = LeaseManager(
            FileLeaseStore(snap), "B", ttl_s=5.0, clock=clock
        )
        svc_b = _fleet_service(
            broker, leases=mgr_b, instance="B",
            follow=follow, snapshot_dir=snap, resume=True,
        )

        def published(t):
            doc = svc_b.state.snapshot(t)
            return doc["overall"]["count"] if doc else -1

        out = {}
        th2 = threading.Thread(
            target=lambda: out.setdefault("fr", svc_b.run_follow())
        )
        th2.start()
        _wait_for(
            lambda: all(
                published(t) >= N_PARTS * FULL_N for t in TOPICS
            ),
            what="instance B to catch up the resumed topics",
        )
        svc_b.request_stop("test")
        th2.join(timeout=60)
    fr = out["fr"]
    # Takeover within one TTL of the crash: every topic was acquired as
    # a takeover (the previous owner was a DIFFERENT, dead instance)
    # and booked as a failover.
    assert _acq("takeover", "B") - take0 == len(TOPICS)
    assert _failovers("B") - fo0 == len(TOPICS)
    # The acceptance proof: resumed-from-the-dead-instance results are
    # byte-identical to the solo referee — no loss, no double-count.
    for t in TOPICS:
        assert _metrics_doc(fr.results[t]) == referee[t]
    # Cross-instance federation on the rollup.
    assert fr.rollup["fleet"]["instance"] == "B"
    assert "B" in fr.rollup["fleet"]["instances"]
    assert svc_b.state.snapshot(TOPICS[0])["instance"] == "B"


# ---------------------------------------------------------------------------
# the paused zombie: late checkpoint write refused at the epoch fence


def test_paused_zombie_is_fenced_at_the_checkpoint(tmp_path):
    """The zombie proof, built on a deterministic freeze.  With
    max_concurrent=1 the lease gate acquires BOTH ready topics but
    admission runs only the heavier one — the backlogged topic's lease
    is held with NO pass in flight, so nothing (in particular not the
    caught-up release at the end of a pass) can strip it before
    pause() freezes the loop at the post-renew gate.  The lease then
    expires mid-freeze, a successor scans the topic and stamps its
    checkpoint with the newer epoch, and on unpause the zombie — whose
    local view still says held-at-epoch-1, and which therefore skips
    the acquire that would have revealed the successor — admits the
    topic and runs a pass whose checkpoint write MUST be refused with
    the named error: status "fenced" (not "failed"), the loss booked
    under the zombie's label, the successor's state untouched."""
    snap = str(tmp_path / "snaps")
    clock = _Clock()
    follow = FollowConfig(**dict(FAST_FOLLOW, checkpoint_every_s=0.0))
    big, zombie = "lease.big", "lease.z"
    records = {
        # More lag on `big`: admission (heaviest-first, one slot) runs
        # it and leaves `zombie` backlogged — lease held, no pass.
        big: _topic_records(3, FULL_N),
        zombie: _topic_records(7, PHASE1_N),
    }
    # The response delay stretches big's pass so the pause lands well
    # before the next poll's gate.
    with _mk_broker(
        records, response_delay=lambda *_: 0.05
    ) as broker:
        store = FileLeaseStore(snap)
        mgr_a = LeaseManager(store, "A", ttl_s=5.0, clock=clock)
        svc = _fleet_service(
            broker, topics=[big, zombie], leases=mgr_a, instance="A",
            follow=follow, snapshot_dir=snap, max_concurrent=1,
        )
        out = {}
        th = threading.Thread(
            target=lambda: out.setdefault("fr", svc.run_follow())
        )
        th.start()
        loss0 = _losses("A")
        try:
            _wait_for(
                lambda: mgr_a.is_held(zombie),
                what="A to hold the backlogged lease",
            )
            svc.pause()
            # `svc.paused` is the gate's own observable: a polls-are-
            # static heuristic cannot tell "frozen at the gate" from
            # "mid-pass on the slow broker", and only at the gate is
            # the held-lease state guaranteed stable.
            _wait_for(
                lambda: svc.paused and mgr_a.is_held(zombie),
                what="A frozen at the gate holding the backlogged lease",
            )
            broker.response_delay = None

            # The zombie window: A's lease expires while it is stalled;
            # a successor takes over and commits its own checkpoint —
            # stamped with the NEWER epoch.
            clock.advance(5.0 + 1.0)
            mgr_b = LeaseManager(store, "B", ttl_s=60.0, clock=clock)
            assert mgr_b.acquire(zombie) == 2
            src_b = _source(broker, zombie)
            res_b = run_scan(
                zombie, src_b, TpuBackend(_cfg(), init_now_s=10**10), 64,
                snapshot_dir=topic_snapshot_dir(snap, zombie),
                final_snapshot=True, lease_epoch=2,
            )
            src_b.close()
            assert res_b.metrics.overall_count == N_PARTS * PHASE1_N

            # The zombie wakes up and admits the topic on its stale
            # epoch-1 view (the lag that makes it ready was measured
            # before the freeze): the checkpoint write MUST be refused.
            svc.unpause()
            _wait_for(
                lambda: svc.scans[zombie].status.status == "fenced",
                what="the zombie's pass to be fenced",
            )
        finally:
            # A failed wait above must not strand the (non-daemon)
            # follow thread at the pause gate — pytest would hang at
            # interpreter exit instead of reporting the failure.
            svc.unpause()
            svc.request_stop("test")
            th.join(timeout=60)
        assert not th.is_alive()
    fr = out["fr"]
    assert svc._stop_reason == "test"  # fenced is NOT all-failed
    assert fr.statuses[zombie].status == "fenced"
    assert "STALE-LEASE-EPOCH" in fr.statuses[zombie].error
    assert _losses("A") - loss0 == 1
    assert not mgr_a.is_held(zombie)
    # B's checkpoint survived the zombie untouched.
    info = snapshot_info(topic_snapshot_dir(snap, zombie))
    assert info["lease_epoch"] == 2
    assert info["records_seen"] == N_PARTS * PHASE1_N
    # The store record is still B's.
    rec, _ = store.read(zombie)
    assert rec.owner == "B" and rec.epoch == 2


# ---------------------------------------------------------------------------
# SIGTERM: shutdown releases every held lease (immediate failover)


def test_sigterm_shutdown_releases_leases(tmp_path):
    """The rolling-restart path.  max_concurrent=1 creates the state
    release_all exists for: the lease gate acquires EVERY ready topic,
    the scheduler admits only one — the backlogged topic's lease is
    held with no pass running.  SIGTERM mid-pass must release it at the
    shutdown boundary so a successor acquires instantly, no TTL wait."""
    snap = str(tmp_path / "snaps")
    clock = _Clock()
    follow = FollowConfig(**dict(FAST_FOLLOW, checkpoint_every_s=0.0))
    phase1 = {t: _topic_records(i, PHASE1_N) for i, t in enumerate(TOPICS)}
    # The response delay stretches the admitted topic's pass so SIGTERM
    # deterministically lands while the backlogged lease is still held.
    with _mk_broker(
        phase1, response_delay=lambda *_: 0.05
    ) as broker:
        store = FileLeaseStore(snap)
        mgr_a = LeaseManager(store, "A", ttl_s=30.0, clock=clock)
        svc = _fleet_service(
            broker, leases=mgr_a, instance="A",
            follow=follow, snapshot_dir=snap, max_concurrent=1,
        )
        restore = svc.install_signal_handlers()
        out = {}
        th = threading.Thread(
            target=lambda: out.setdefault("fr", svc.run_follow())
        )
        try:
            th.start()
            _wait_for(
                lambda: set(mgr_a.held_topics()) == set(TOPICS),
                what="instance A to hold every topic lease",
            )
            os.kill(os.getpid(), signal.SIGTERM)
            th.join(timeout=60)
        finally:
            restore()
        assert not th.is_alive()
        assert svc._stop_reason == "SIGTERM"
        # Every lease was RELEASED at shutdown (owner None, epoch kept —
        # records are never deleted): a successor acquires instantly at
        # the SAME frozen clock, no TTL wait.
        for t in TOPICS:
            rec, _ = store.read(t)
            assert rec is not None and rec.owner is None
            assert rec.epoch == 1
        assert store.owners() == set()
        mgr_b = LeaseManager(store, "B", ttl_s=30.0, clock=clock)
        for t in TOPICS:
            assert mgr_b.acquire(t) == 2
        # Whatever was scanned was checkpointed to the head before the
        # release (per-pass forced checkpoints) — the successor resumes,
        # it does not rescan.
        inv = list_topic_snapshots(snap)
        assert inv  # the admitted topic completed at least one pass
        assert all(
            info["records_seen"] == N_PARTS * PHASE1_N
            and info["lease_epoch"] == 1
            for info in inv.values()
        )
