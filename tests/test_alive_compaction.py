"""Host-side LWW alive-pair compaction (ISSUE 12, DESIGN §19).

The byte-identity bar: a compacted scan (``--alive-compaction auto``, the
wire-v5 default — pairs leave the per-row sections and ship as ONE
LWW-merged per-dispatch table applied after the scan) must equal the
uncompacted scan byte-for-byte across (wire, segfile) × workers × K ×
mesh, under corruption/quarantine rewind, across resume, and across
follow passes.  The algebra bar: host compaction ∘ device merge must
equal the uncompacted per-record fold over generated update streams —
duplicate slots within and across frames, tombstone↔set flips, arbitrary
batch and superbatch splits — for BOTH the native and numpy packers
(the hypothesis property test).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    CorruptionConfig,
    DispatchConfig,
    FollowConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.obs.registry import default_registry
from kafka_topic_analyzer_tpu.packing import (
    batch_alive_pairs,
    pack_batch,
    pack_pair_table,
    packed_nbytes,
    pair_table_capacity,
    pair_table_nbytes,
    unpack_numpy,
    unpack_pair_table_numpy,
)
from kafka_topic_analyzer_tpu.records import RecordBatch

from fake_broker import CorruptionInjector, FakeBroker

pytestmark = pytest.mark.alivecompact

TOPIC = "compact.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}


def _mk_records(partition: int, n: int):
    # Dense key reuse + frequent tombstones: the LWW order-sensitivity
    # this feature must preserve, and real cross-batch duplication for
    # the compaction ratio to bite on.
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 17}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 3 else None,
        )
        for i in range(n)
    ]


N_PARTS = 4
N_REC = 300
RECORDS = {p: _mk_records(p, N_REC) for p in range(N_PARTS)}


def _cfg(compaction: str, **kw) -> AnalyzerConfig:
    base = dict(
        num_partitions=N_PARTS,
        batch_size=128,
        count_alive_keys=True,
        alive_bitmap_bits=16,
        enable_hll=True,
        hll_p=8,
        enable_quantiles=True,
        wire_format=5,
    )
    base.update(kw)
    return AnalyzerConfig(alive_compaction=compaction, **base)


def _full_doc(result) -> dict:
    return {
        "metrics": result.metrics.to_dict(
            result.start_offsets, result.end_offsets
        ),
        "start": result.start_offsets,
        "end": result.end_offsets,
        "degraded": result.degraded_partitions,
        "corrupt": result.corrupt_partitions,
    }


def _wire_scan(compaction, workers=1, superbatch=1, backend_cls=TpuBackend,
               mesh=None, **cfg_kw):
    cfg = _cfg(compaction, **cfg_kw)
    if mesh is not None:
        cfg = dataclasses.replace(cfg, mesh_shape=mesh)
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        backend = backend_cls(
            cfg, init_now_s=10**10,
            dispatch=DispatchConfig(superbatch=superbatch),
        )
        result = run_scan(
            TOPIC, src, backend, cfg.batch_size, ingest_workers=workers
        )
        src.close()
    return result


@pytest.fixture(scope="module")
def uncompacted_baseline():
    """The --alive-compaction off scan — the byte-exact referee."""
    return _full_doc(_wire_scan("off"))


# ---------------------------------------------------------------------------
# scan-level identity: (wire) × workers × K × mesh


@pytest.mark.parametrize("workers,superbatch", [
    (1, 1), (4, 1), (1, 4), (4, 4),
])
def test_compacted_wire_scan_identical(
    uncompacted_baseline, workers, superbatch
):
    result = _wire_scan("auto", workers=workers, superbatch=superbatch)
    assert _full_doc(result) == uncompacted_baseline
    assert result.wire is not None
    assert result.wire.alive_compaction == "on"
    assert result.wire.pairs_emitted > 0
    assert result.wire.pairs_raw >= result.wire.pairs_emitted
    if superbatch > 1 and workers == 1:
        # Cross-batch dedupe only exists at K>1, and only when one
        # dispatch sees the same partition more than once (the 4-worker
        # fan-in gives each superbatch one batch per partition — disjoint
        # key spaces, honestly ratio 1.0).  Sequential ingest repeats the
        # 17-key cycle within a superbatch, so the ratio must bite here.
        assert result.wire.compaction_ratio < 1.0


@pytest.mark.parametrize("mesh,superbatch", [
    ((2, 1), 1), ((2, 1), 4), ((2, 2), 1), ((2, 2), 4),
])
def test_compacted_sharded_scan_identical(
    uncompacted_baseline, mesh, superbatch
):
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    for compaction in ("off", "auto"):
        result = _wire_scan(
            compaction, mesh=mesh, superbatch=superbatch,
            backend_cls=ShardedTpuBackend,
        )
        assert _full_doc(result) == uncompacted_baseline, (mesh, compaction)


def test_compacted_rows_drop_pair_sections():
    """The wire saving is structural: compacted v5 rows carry NO alive
    sections (5 B/record gone), and the per-dispatch pair table is the
    only place pairs travel — with identical LWW content."""
    from kafka_topic_analyzer_tpu.packing import _sections

    cfg_on = _cfg("auto")
    cfg_off = _cfg("off")
    names_on = {n for n, _, _ in _sections(cfg_on, 128)}
    assert "alive_slot" not in names_on and "alive_flag" not in names_on
    assert (packed_nbytes(cfg_off, 128) - packed_nbytes(cfg_on, 128)
            == 128 * 5)

    spec = SyntheticSpec(
        num_partitions=2, messages_per_partition=200,
        keys_per_partition=11, tombstone_permille=300, seed=9,
    )
    batch = next(SyntheticSource(spec).batches(128))
    for use_native in (False, True):
        if use_native:
            native = pytest.importorskip(
                "kafka_topic_analyzer_tpu.io.native"
            )
            if not native.native_available():
                pytest.skip("native shim unavailable")
        row = pack_batch(batch, cfg_on, use_native=use_native)
        assert int(unpack_numpy(row.copy(), cfg_on)["n_pairs"]) == 0
        off_row = unpack_numpy(
            pack_batch(batch, cfg_off, use_native=use_native).copy(), cfg_off
        )
        n_off = int(off_row["n_pairs"])
        cap = pair_table_capacity(cfg_on, 128, 1)
        tbl, raw, emitted = pack_pair_table(
            [batch_alive_pairs(batch, cfg_on, use_native)],
            cfg_on, cap, use_native=use_native,
        )
        assert tbl.nbytes == pair_table_nbytes(cfg_on, cap)
        ut = unpack_pair_table_numpy(tbl, cfg_on, cap)
        assert int(ut["n_pairs"]) == n_off == emitted
        # bits=16 picks the MASK form: reconstruct the per-slot LWW map
        # from the set/clear words and compare against the off-path pairs.
        assert "alive_set" in ut
        got = {}
        for w, (sw, cw) in enumerate(zip(
            np.asarray(ut["alive_set"]).tolist(),
            np.asarray(ut["alive_clear"]).tolist(),
        )):
            for bit in range(32):
                if sw & (1 << bit):
                    got[w * 32 + bit] = 1
                elif cw & (1 << bit):
                    got[w * 32 + bit] = 0
        assert got == dict(
            zip(off_row["alive_slot"][:n_off].tolist(),
                off_row["alive_flag"][:n_off].tolist()))


# ---------------------------------------------------------------------------
# segfile cold path


def test_compacted_segfile_scan_identical(tmp_path):
    from kafka_topic_analyzer_tpu.io.segfile import (
        SegmentDumpWriter,
        SegmentFileSource,
    )

    spec = SyntheticSpec(
        num_partitions=3, messages_per_partition=700, keys_per_partition=40,
        seed=5, key_null_permille=60, tombstone_permille=200,
    )
    d = str(tmp_path / "segs")
    writer = SegmentDumpWriter(d, "seg.topic", records_per_chunk=256)
    src = SyntheticSource(spec)
    writer.set_base_offsets(src.watermarks()[0])
    for b in src.batches(180):
        writer.append(b)
    writer.close()

    def scan(compaction, workers=1, superbatch=1):
        cfg = AnalyzerConfig(
            num_partitions=3, batch_size=128, count_alive_keys=True,
            alive_bitmap_bits=14, enable_hll=True, hll_p=8,
            wire_format=5, alive_compaction=compaction,
        )
        s = SegmentFileSource(d, "seg.topic")
        r = run_scan(
            "seg.topic", s,
            TpuBackend(cfg, init_now_s=10**10,
                       dispatch=DispatchConfig(superbatch=superbatch)),
            128, ingest_workers=workers,
        )
        return _full_doc(r)

    base = scan("off")
    assert scan("auto") == base
    assert scan("auto", workers=2) == base
    assert scan("auto", superbatch=4) == base


# ---------------------------------------------------------------------------
# corruption / quarantine rewind parity


def test_compacted_corruption_quarantine_parity(tmp_path):
    """Deterministic poison under --on-corruption=quarantine: the
    compacted scan classifies, accounts, and quarantines EXACTLY like the
    uncompacted one — frame rewind must leave the pair emission region as
    atomic as the row sections (pairs only emit after a frame validates)."""
    def poisoned():
        inj = (
            CorruptionInjector()
            .flip_byte(1, chunk=1, offset=-1)
            .flip_byte(2, chunk=3, offset=-3)
        )
        return FakeBroker(
            TOPIC, RECORDS, max_records_per_fetch=50, corruption=inj,
            honor_partition_max_bytes=True,
        )

    def run(compaction, qdir, superbatch=1):
        cfg = _cfg(compaction)
        with poisoned() as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC,
                overrides=dict(FAST_RETRY, **{"check.crcs": "true"}),
                corruption=CorruptionConfig(
                    policy="quarantine", quarantine_dir=qdir
                ),
            )
            r = run_scan(
                TOPIC, src,
                TpuBackend(cfg, init_now_s=10**10,
                           dispatch=DispatchConfig(superbatch=superbatch)),
                128,
            )
            spans = src.corruption_spans()
            src.close()
        return _full_doc(r), spans

    doc_off, spans_off = run("off", str(tmp_path / "qoff"))
    doc_on, spans_on = run("auto", str(tmp_path / "qon"))
    doc_on_k, spans_on_k = run("auto", str(tmp_path / "qonk"), superbatch=4)
    assert doc_on == doc_off
    assert doc_on_k == doc_off
    assert sorted(doc_on["corrupt"]) == [1, 2]
    assert spans_on == spans_off == spans_on_k
    assert sorted(os.listdir(tmp_path / "qon")) == sorted(
        os.listdir(tmp_path / "qoff")
    )


# ---------------------------------------------------------------------------
# cross-config resume (compaction is execution strategy)


class _Interrupt(Exception):
    pass


class _InterruptingSource(SyntheticSource):
    def __init__(self, spec, limit):
        super().__init__(spec)
        self.limit = limit

    def batches(self, batch_size, partitions=None, start_at=None):
        it = super().batches(batch_size, partitions, start_at)
        for i, b in enumerate(it):
            if start_at is None and i >= self.limit:
                raise _Interrupt()
            yield b


RESUME_SPEC = SyntheticSpec(
    num_partitions=3, messages_per_partition=2_000, keys_per_partition=80,
    tombstone_permille=250, seed=31,
)


@pytest.mark.parametrize("first,second", [("auto", "off"), ("off", "auto")])
def test_cross_compaction_resume(tmp_path, first, second):
    """A snapshot taken mid-scan with compaction one way resumes the
    other way, reproducing the uninterrupted scan exactly — the setting
    is execution strategy, outside the checkpoint fingerprint."""
    cfg_first = AnalyzerConfig(
        num_partitions=3, batch_size=512, count_alive_keys=True,
        alive_bitmap_bits=18, enable_hll=True, hll_p=10,
        wire_format=5, alive_compaction=first,
    )
    cfg_second = dataclasses.replace(cfg_first, alive_compaction=second)
    full = run_scan(
        "t", SyntheticSource(RESUME_SPEC),
        TpuBackend(cfg_second, init_now_s=10**10), 512,
    ).metrics.to_dict(None, None)

    with pytest.raises(_Interrupt):
        run_scan(
            "t", _InterruptingSource(RESUME_SPEC, limit=5),
            TpuBackend(cfg_first, init_now_s=10**10,
                       dispatch=DispatchConfig(superbatch=2)), 512,
            snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
        )
    resumed = run_scan(
        "t", SyntheticSource(RESUME_SPEC),
        TpuBackend(cfg_second, init_now_s=0), 512,
        snapshot_dir=str(tmp_path), resume=True,
    )
    assert resumed.metrics.to_dict(None, None) == full


# ---------------------------------------------------------------------------
# follow mode: pass-chained folds with compaction on == batch scan


def _wait_for(predicate, timeout_s=30.0, interval_s=0.01, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


def test_follow_compacted_matches_batch():
    from kafka_topic_analyzer_tpu.serve.follow import FollowService

    phase1 = {p: RECORDS[p][:200] for p in range(N_PARTS)}
    phase2 = {p: RECORDS[p][200:] for p in range(N_PARTS)}
    total = N_PARTS * N_REC

    def followed(compaction):
        cfg = _cfg(compaction, batch_size=64)
        follow = FollowConfig(
            poll_interval_s=0.02, idle_backoff_max_s=0.05, window_count=0
        )
        with FakeBroker(TOPIC, phase1, max_records_per_fetch=48) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            )
            svc = FollowService(
                TOPIC, src,
                TpuBackend(cfg, init_now_s=10**10,
                           dispatch=DispatchConfig(superbatch=4)),
                64, follow,
            )
            errors = []

            def folded():
                doc = svc.state.snapshot()
                return doc["overall"]["count"] if doc else -1

            def driver():
                try:
                    _wait_for(
                        lambda: folded() >= N_PARTS * 200,
                        what="phase-1 fold",
                    )
                    for p in range(N_PARTS):
                        broker.produce(p, phase2[p])
                    _wait_for(
                        lambda: folded() >= total, what="phase-2 fold"
                    )
                except BaseException as e:
                    errors.append(e)
                finally:
                    svc.request_stop("test")

            t = threading.Thread(target=driver)
            t.start()
            result = svc.run()
            t.join()
            src.close()
            if errors:
                raise errors[0]
        return result.metrics.to_dict(None, None)

    batch = _wire_scan("off").metrics.to_dict(None, None)
    assert followed("auto") == batch
    assert followed("off") == batch


# ---------------------------------------------------------------------------
# compaction algebra: host compaction ∘ device merge ≡ per-record fold
# (hypothesis property test, both packers)


def _reference_alive_count(stream, bits):
    """Pure-python per-record LWW replay: the metric's DEFINITION."""
    alive = {}
    mask = (1 << bits) - 1
    for batch in stream:
        nv = batch.num_valid
        for i in range(nv):
            if batch.key_null[i]:
                continue
            alive[int(batch.key_hash32[i]) & mask] = not batch.value_null[i]
    return sum(1 for v in alive.values() if v)


def _bitmap_words(table_groups, cfg):
    """Apply per-dispatch compacted tables in order through the DEVICE
    merge — pair-scatter or elementwise-mask form, exactly as
    backends.step.apply_pair_table dispatches on the section names."""
    from kafka_topic_analyzer_tpu.jax_support import jnp
    from kafka_topic_analyzer_tpu.ops.bitmap import (
        bitmap_apply_masks,
        bitmap_apply_pairs,
        bitmap_num_words,
        bitmap_popcount,
    )

    words = jnp.zeros(
        (bitmap_num_words(cfg.alive_bitmap_bits),), dtype=jnp.uint32
    )
    for ut in table_groups:
        if "alive_set" in ut:
            words = bitmap_apply_masks(
                words,
                jnp.asarray(np.asarray(ut["alive_set"])),
                jnp.asarray(np.asarray(ut["alive_clear"])),
                bits=cfg.alive_bitmap_bits,
            )
        else:
            words = bitmap_apply_pairs(
                words,
                jnp.asarray(np.asarray(ut["alive_slot"])),
                jnp.asarray(np.asarray(ut["alive_flag"])),
                jnp.int32(int(ut["n_pairs"])),
                bits=cfg.alive_bitmap_bits,
            )
    return int(bitmap_popcount(words))


def _stream_batch(parts, records):
    n = len(records)
    key_null = np.array([r[0] is None for r in records], dtype=bool)
    value_null = np.array([r[1] for r in records], dtype=bool)
    h32 = np.array([0 if r[0] is None else r[0] for r in records],
                   dtype=np.uint32)
    return RecordBatch(
        partition=np.zeros(n, dtype=np.int32),
        key_len=np.where(key_null, 0, 3).astype(np.int32),
        value_len=np.where(value_null, 0, 5).astype(np.int32),
        key_null=key_null,
        value_null=value_null,
        ts_s=np.arange(n, dtype=np.int64),
        key_hash32=h32,
        key_hash64=h32.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15),
        valid=np.ones(n, dtype=bool),
    )


def test_compaction_algebra_matches_per_record_fold():
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    native = pytest.importorskip("kafka_topic_analyzer_tpu.io.native")
    use_native = native.native_available()

    @settings(max_examples=50, deadline=None)
    @given(st.data())
    def run(data):
        bits = data.draw(st.integers(min_value=3, max_value=8))
        # A stream of (key-hash-or-None, tombstone?) updates over a TINY
        # slot space: duplicates within and across batches, tombstone↔set
        # flips, guaranteed.
        n = data.draw(st.integers(min_value=0, max_value=120))
        updates = [
            (
                None
                if data.draw(st.booleans()) and data.draw(st.booleans())
                else data.draw(st.integers(0, 2**32 - 1)),
                data.draw(st.booleans()),
            )
            for _ in range(n)
        ]
        # Arbitrary batch split, then arbitrary superbatch (dispatch)
        # grouping of those batches.
        cuts = sorted(data.draw(
            st.lists(st.integers(0, n), max_size=6)
        )) + [n]
        batches, lo = [], 0
        for hi in cuts:
            if hi > lo:
                batches.append(_stream_batch(1, updates[lo:hi]))
                lo = hi
        k = data.draw(st.integers(min_value=1, max_value=4))
        # bits 26 forces the bounded PAIR form at this tiny capacity;
        # small bits take the mask form — both kernels must agree.
        if data.draw(st.booleans()) and data.draw(st.booleans()):
            bits = 26
        cfg = AnalyzerConfig(
            num_partitions=1, batch_size=128, count_alive_keys=True,
            alive_bitmap_bits=bits, wire_format=5,
        )
        ref = _reference_alive_count(batches, bits)
        for nat in ([False, True] if use_native else [False]):
            groups = []
            for g in range(0, len(batches), k):
                cap = pair_table_capacity(cfg, 128, k)
                tbl, _, _ = pack_pair_table(
                    [
                        batch_alive_pairs(b, cfg, use_native=nat)
                        for b in batches[g : g + k]
                    ],
                    cfg, cap, use_native=nat,
                )
                groups.append(unpack_pair_table_numpy(tbl, cfg, cap))
            assert _bitmap_words(groups, cfg) == ref, (nat, bits, k)

    run()


def test_compaction_algebra_seeded_sweep():
    """Seeded twin of the hypothesis property above — the same
    compaction ∘ merge ≡ per-record-fold check runs even where the
    hypothesis package is absent (tier-1 containers)."""
    try:
        from kafka_topic_analyzer_tpu.io.native import native_available

        use_native = native_available()
    except ImportError:
        use_native = False
    rng = np.random.default_rng(0xC0FFEE)
    for trial in range(40):
        bits = int(rng.integers(3, 9))
        n = int(rng.integers(0, 121))
        updates = [
            (
                None if rng.random() < 0.2 else int(rng.integers(0, 2**32)),
                bool(rng.random() < 0.4),
            )
            for _ in range(n)
        ]
        cuts = sorted(rng.integers(0, n + 1, size=int(rng.integers(0, 6))).tolist()) + [n]
        batches, lo = [], 0
        for hi in cuts:
            if hi > lo:
                batches.append(_stream_batch(1, updates[lo:hi]))
                lo = hi
        k = int(rng.integers(1, 5))
        if trial % 8 == 7:
            bits = 26  # the bounded PAIR form (masks past the trade cap)
        cfg = AnalyzerConfig(
            num_partitions=1, batch_size=128, count_alive_keys=True,
            alive_bitmap_bits=bits, wire_format=5,
        )
        from kafka_topic_analyzer_tpu.packing import alive_table_mode
        assert alive_table_mode(cfg, pair_table_capacity(cfg, 128, k)) == (
            1 if bits == 26 else 2
        )
        ref = _reference_alive_count(batches, bits)
        for nat in ([False, True] if use_native else [False]):
            groups = []
            for g in range(0, len(batches), k):
                cap = pair_table_capacity(cfg, 128, k)
                tbl, _, _ = pack_pair_table(
                    [
                        batch_alive_pairs(b, cfg, use_native=nat)
                        for b in batches[g : g + k]
                    ],
                    cfg, cap, use_native=nat,
                )
                groups.append(unpack_pair_table_numpy(tbl, cfg, cap))
            assert _bitmap_words(groups, cfg) == ref, (trial, nat, bits, k)


# ---------------------------------------------------------------------------
# gating, kill switches, accounting


def _metric_total(name: str) -> float:
    m = default_registry().snapshot().get(name)
    return sum(s["value"] for s in m["samples"]) if m else 0.0


def test_compaction_resolution_and_kill_switches(monkeypatch):
    on = AnalyzerConfig(num_partitions=2, batch_size=64,
                        count_alive_keys=True)
    assert on.compact_alive and on.alive_compaction_off_reason is None

    off = AnalyzerConfig(num_partitions=2, batch_size=64,
                         count_alive_keys=True, alive_compaction="off")
    assert not off.compact_alive
    assert off.alive_compaction_off_reason == "explicit"

    v4 = AnalyzerConfig(num_partitions=2, batch_size=64,
                        count_alive_keys=True, wire_format=4)
    assert not v4.compact_alive
    assert v4.alive_compaction_off_reason == "wire-v4"

    monkeypatch.setenv("KTA_DISABLE_COMPACTION", "1")
    env = AnalyzerConfig(num_partitions=2, batch_size=64,
                         count_alive_keys=True)
    assert not env.compact_alive
    assert env.alive_compaction_off_reason == "env-kill-switch"
    monkeypatch.delenv("KTA_DISABLE_COMPACTION")

    no_alive = AnalyzerConfig(num_partitions=2, batch_size=64)
    assert not no_alive.compact_alive
    assert no_alive.alive_compaction_off_reason is None

    with pytest.raises(ValueError, match="alive_compaction"):
        AnalyzerConfig(num_partitions=2, batch_size=64,
                       alive_compaction="maybe")


def test_pair_counters_and_fallback_booked():
    before_raw = _metric_total("kta_alive_pairs_raw_total")
    before_em = _metric_total("kta_alive_pairs_emitted_total")
    before_off = _metric_total("kta_alive_compaction_off_total")

    result = _wire_scan("auto", superbatch=4)
    raw = _metric_total("kta_alive_pairs_raw_total") - before_raw
    em = _metric_total("kta_alive_pairs_emitted_total") - before_em
    assert raw > 0 and 0 < em <= raw
    assert result.wire.pairs_raw == int(raw)
    assert result.wire.pairs_emitted == int(em)
    assert _metric_total("kta_alive_compaction_off_total") == before_off

    off_result = _wire_scan("off")
    assert (
        _metric_total("kta_alive_compaction_off_total") == before_off + 1
    )
    assert off_result.wire.alive_compaction == "off (explicit)"
    assert off_result.wire.pairs_raw == 0


def test_stats_compaction_line_renders():
    from kafka_topic_analyzer_tpu.report import render_telemetry_stats

    result = _wire_scan("auto", superbatch=4)
    text = render_telemetry_stats(result.telemetry, wire=result.wire)
    assert "alive-compaction: on" in text
    assert "ratio" in text

    off = _wire_scan("off")
    text_off = render_telemetry_stats(off.telemetry, wire=off.wire)
    assert "alive-compaction: off (explicit)" in text_off
    assert off.wire.as_dict()["alive_compaction"] == "off (explicit)"
    doc = result.wire.as_dict()
    assert doc["alive_pairs_raw"] == result.wire.pairs_raw
    assert 0 < doc["alive_compaction_ratio"] <= 1


def test_worst_case_all_unique_ratio_is_one():
    """All-unique keys: compaction cannot dedupe anything — the ratio is
    honestly 1.0 and results still match the uncompacted fold."""
    spec = SyntheticSpec(
        num_partitions=2, messages_per_partition=1500,
        keys_per_partition=1_000_000, tombstone_permille=100, seed=13,
    )

    def scan(compaction):
        cfg = AnalyzerConfig(
            num_partitions=2, batch_size=256, count_alive_keys=True,
            alive_bitmap_bits=24, wire_format=5,
            alive_compaction=compaction,
        )
        return run_scan(
            "t", SyntheticSource(spec),
            TpuBackend(cfg, init_now_s=10**10,
                       dispatch=DispatchConfig(superbatch=4)),
            256,
        )

    on = scan("auto")
    off = scan("off")
    assert on.metrics.to_dict(None, None) == off.metrics.to_dict(None, None)
    # Not exactly 1.0 only if the 1M-key draw collides; allow a hair.
    assert on.wire.compaction_ratio > 0.99


# ---------------------------------------------------------------------------
# mesh-pinned alive resume rejection names the feature + allowed configs


def test_mesh_pinned_resume_error_names_feature(tmp_path):
    from kafka_topic_analyzer_tpu.checkpoint import (
        load_snapshot,
        save_snapshot,
    )
    from kafka_topic_analyzer_tpu.models.state import AnalyzerState

    cfg21 = AnalyzerConfig(
        num_partitions=4, batch_size=128, count_alive_keys=True,
        alive_bitmap_bits=12, mesh_shape=(2, 1),
    )
    save_snapshot(
        str(tmp_path), "t", cfg21, AnalyzerState.init(cfg21),
        {0: 5}, 5, 0,
    )
    cfg11 = dataclasses.replace(cfg21, mesh_shape=(1, 1))
    with pytest.raises(ValueError) as ei:
        load_snapshot(str(tmp_path), "t", cfg11)
    msg = str(ei.value)
    assert "MESH-PINNED" in msg
    assert "count-alive-keys" in msg
    assert "--mesh 2,1" in msg          # the config that may resume it
    assert "--mesh 1,1" in msg          # what a rescan would run
    # A genuinely different config (other topic) stays a generic mismatch
    # but still names the mesh-pinning rule for alive scans.
    with pytest.raises(ValueError, match="alive keys"):
        load_snapshot(str(tmp_path), "other-topic".replace("-", "_"),
                      dataclasses.replace(cfg11, num_partitions=5))
