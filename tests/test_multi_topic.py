"""Multi-topic fan-in: per-topic slices must equal standalone scans, and
the union must equal the sum/merge of parts."""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.cli import main
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.multi import MultiTopicSource
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.results import slice_rows


def _spec(seed, partitions=2, messages=2000):
    return SyntheticSpec(
        num_partitions=partitions,
        messages_per_partition=messages,
        keys_per_partition=100,
        tombstone_permille=150,
        seed=seed,
    )


def test_fan_in_slices_match_standalone_scans():
    specs = {"alpha": _spec(1, 2, 1500), "beta": _spec(2, 3, 2200)}
    multi = MultiTopicSource([(t, SyntheticSource(s)) for t, s in specs.items()])
    cfg = AnalyzerConfig(num_partitions=5, batch_size=512)
    union = run_scan("m", multi, TpuBackend(cfg, init_now_s=10**10), 512).metrics

    for topic, spec in specs.items():
        solo_cfg = AnalyzerConfig(num_partitions=spec.num_partitions, batch_size=512)
        solo = run_scan(
            topic, SyntheticSource(spec),
            CpuExactBackend(solo_cfg, init_now_s=10**10), 512,
        ).metrics
        rows = multi.rows_for(topic)
        ids = [multi.true_partition(r) for r in rows]
        sliced = slice_rows(union, rows, ids)
        assert np.array_equal(sliced.per_partition, solo.per_partition)
        assert sliced.earliest_ts_s == solo.earliest_ts_s
        assert sliced.latest_ts_s == solo.latest_ts_s
        assert sliced.smallest_message == solo.smallest_message
        assert sliced.largest_message == solo.largest_message
        assert sliced.overall_count == solo.overall_count
        assert sliced.overall_size == solo.overall_size

    assert union.overall_count == 2 * 1500 + 3 * 2200


def test_union_alive_keys_is_sum_of_per_topic_counts():
    # Aliveness is tracked per (topic, key) — slots are salted per topic so
    # the count is mesh/interleaving-independent (io/multi.py docstring).
    # Identical topics therefore count twice.
    spec = _spec(7, 1, 800)
    multi = MultiTopicSource(
        [("a", SyntheticSource(spec)), ("b", SyntheticSource(spec))]
    )
    cfg = AnalyzerConfig(
        num_partitions=2, batch_size=256, count_alive_keys=True,
        alive_bitmap_bits=20,
    )
    union = run_scan("m", multi, TpuBackend(cfg, init_now_s=0), 256).metrics
    solo = run_scan(
        "a", SyntheticSource(spec),
        CpuExactBackend(
            AnalyzerConfig(num_partitions=1, batch_size=256,
                           count_alive_keys=True, alive_bitmap_bits=20),
            init_now_s=0,
        ), 256,
    ).metrics
    assert union.alive_keys == 2 * solo.alive_keys


def test_fan_in_alive_keys_mesh_independent():
    """The same fan-in scan must report identical alive keys on any mesh."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    specs = [("a", _spec(3, 2, 900)), ("b", _spec(4, 2, 900))]
    counts = []
    for mesh in [(1, 1), (4, 1)]:
        cfg = AnalyzerConfig(
            num_partitions=4, batch_size=256, count_alive_keys=True,
            alive_bitmap_bits=20, mesh_shape=mesh,
        )
        multi = MultiTopicSource([(t, SyntheticSource(s)) for t, s in specs])
        backend = (
            TpuBackend(cfg, init_now_s=0)
            if mesh == (1, 1)
            else ShardedTpuBackend(cfg, init_now_s=0)
        )
        counts.append(run_scan("m", multi, backend, 256).metrics.alive_keys)
    assert counts[0] == counts[1]


def test_fan_in_from_timestamp():
    """--from-timestamp over multi-topic fan-in: each topic's broker
    timestamp index resolves to row-space start offsets."""
    import sys

    sys.path.insert(0, "tests")
    from fake_broker import FakeBroker

    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    def rows(n):
        return [(i, 1_600_000_000_000 + i * 1000, f"k{i}".encode(), bytes(10))
                for i in range(n)]

    with FakeBroker("alpha", {0: rows(100)}) as b1, \
         FakeBroker("beta", {0: rows(60)}) as b2:
        multi = MultiTopicSource([
            ("alpha", KafkaWireSource(f"127.0.0.1:{b1.port}", "alpha")),
            ("beta", KafkaWireSource(f"127.0.0.1:{b2.port}", "beta")),
        ])
        cutoff = 1_600_000_000_000 + 39_500  # first record >= : offset 40
        start_at = multi.offsets_for_timestamp(cutoff)
        assert start_at == {0: 40, 1: 40}
        cfg = AnalyzerConfig(num_partitions=2, batch_size=64)
        m = run_scan(
            "m", multi, CpuExactBackend(cfg, init_now_s=10**10), 64,
            start_at=start_at,
        ).metrics
        multi.close()
    assert m.overall_count == (100 - 40) + (60 - 40)
    assert m.earliest_ts_s == (1_600_000_000_000 + 40_000) // 1000


def test_cli_fan_in_from_timestamp(capsys, monkeypatch):
    """The full CLI path for -t a,b --from-timestamp: validation no longer
    rejects the combination, each topic resolves its own timestamp index,
    and the per-topic reports reflect the cutoff."""
    import sys

    sys.path.insert(0, "tests")
    from fake_broker import FakeBroker

    import kafka_topic_analyzer_tpu.cli as cli_mod
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    def rows(n):
        return [(i, 1_600_000_000_000 + i * 1000, f"k{i}".encode(), bytes(10))
                for i in range(n)]

    with FakeBroker("alpha", {0: rows(100)}) as b1, \
         FakeBroker("beta", {0: rows(60)}) as b2:
        ports = {"alpha": b1.port, "beta": b2.port}

        def make_source(args, topic=None, seed_salt=0):
            t = topic or args.topic
            return KafkaWireSource(f"127.0.0.1:{ports[t]}", t)

        monkeypatch.setattr(cli_mod, "make_source", make_source)
        rc = main([
            "-t", "alpha,beta", "-b", "ignored:9092",
            "--from-timestamp", str(1_600_000_000_000 + 39_500),
            "--backend", "cpu", "--quiet",
        ])
        assert rc == 0
    out = capsys.readouterr().out
    assert "Topic alpha" in out and "Topic beta" in out
    assert "Messages: 80" in out  # union: 60 + 20 after the cutoff


def test_duplicate_topics_rejected():
    spec = _spec(1)
    with pytest.raises(ValueError, match="duplicate"):
        MultiTopicSource(
            [("x", SyntheticSource(spec)), ("x", SyntheticSource(spec))]
        )


def test_cli_fan_in(capsys):
    assert main([
        "-t", "north,south,east",
        "--source", "synthetic",
        "--synthetic", "partitions=2,messages=400,keys=50,tombstones=150",
        "--backend", "tpu", "-c", "--alive-bitmap-bits", "20",
        "--distinct-keys",
        "--quiet", "--native", "off",
    ]) == 0
    out = capsys.readouterr().out
    assert out.count("Calculating statistics...") == 3  # one report per topic
    assert "Topic north" in out and "Topic east" in out
    assert "FAN-IN UNION of 3 topics" in out
    assert "Messages: 2400" in out  # 3 topics * 2 partitions * 400
    assert "Alive keys (sum over topics):" in out
    assert "Distinct keys (HLL est., union):" in out
