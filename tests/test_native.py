"""Native C++ ingest shim: bit-parity with the numpy generator and hashers."""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.ops.fnv import fnv1a32_ref, fnv1a64
from kafka_topic_analyzer_tpu.records import RecordBatch

native = pytest.importorskip("kafka_topic_analyzer_tpu.io.native")

if not native.native_available():  # pragma: no cover
    pytest.skip("native shim could not be built", allow_module_level=True)

SPEC = SyntheticSpec(
    num_partitions=5,
    messages_per_partition=4_000,
    keys_per_partition=123,
    key_null_permille=70,
    tombstone_permille=130,
    value_len_min=5,
    value_len_max=500,
    seed=0xABCD,
)


def test_native_generator_bit_parity():
    py_src = SyntheticSource(SPEC)
    nat_src = native.NativeSyntheticSource(SPEC)
    a = RecordBatch.concat(list(py_src.batches(1024)))
    b = RecordBatch.concat(list(nat_src.batches(1024)))
    for name, _ in RecordBatch.FIELDS:
        assert np.array_equal(getattr(a, name), getattr(b, name)), name


def test_native_generator_partition_slice_parity():
    py_src = SyntheticSource(SPEC)
    nat_src = native.NativeSyntheticSource(SPEC)
    a = RecordBatch.concat(list(py_src.batches(700, partitions=[1, 4])))
    b = RecordBatch.concat(list(nat_src.batches(700, partitions=[1, 4])))
    for name, _ in RecordBatch.FIELDS:
        assert np.array_equal(getattr(a, name), getattr(b, name)), name


def test_native_hash_batch_matches_scalar():
    rng = np.random.default_rng(1)
    slices = [rng.integers(0, 256, size=int(n), dtype=np.uint8).tobytes()
              for n in rng.integers(0, 40, size=257)]
    data = b"".join(slices)
    offsets = np.zeros(len(slices) + 1, dtype=np.int64)
    np.cumsum([len(s) for s in slices], out=offsets[1:])
    h32, h64 = native.hash_batch_native(data, offsets)
    for i, s in enumerate(slices):
        assert int(h32[i]) == fnv1a32_ref(s)
        assert int(h64[i]) == fnv1a64(s)
