"""End-to-end CLI runs (synthetic source, cpu + tpu backends)."""

import pytest

from kafka_topic_analyzer_tpu.cli import main, parse_kv_pairs, parse_mesh


def test_parse_kv_pairs():
    assert parse_kv_pairs("a=b,c=d") == {"a": "b", "c": "d"}
    assert parse_kv_pairs(None) == {}


def test_parse_mesh():
    assert parse_mesh("4") == (4, 1)
    assert parse_mesh("4,2") == (4, 2)


def test_help_and_version_surface(capsys):
    """-h/--help/--version must exit 0 and render, like clap's
    (src/main.rs:32-67 — the reference's help cannot crash).

    Regression: a bare ``%`` in an argparse help string makes
    ``format_help()`` raise ValueError at print time (r2-r3 shipped one in
    the --pallas help), so every registered action's help is formatted
    here, not just spot-checked flags.
    """
    from kafka_topic_analyzer_tpu.cli import build_parser

    parser = build_parser()
    # Every action's help string must survive argparse's %-interpolation.
    formatter = parser._get_formatter()
    for action in parser._actions:
        if action.help:
            # Same interpolation argparse applies inside format_help().
            formatter._expand_help(action)
    full = parser.format_help()
    assert "--pallas" in full and "--topic" in full

    for flag in ("-h", "--help"):
        with pytest.raises(SystemExit) as e:
            main([flag])
        assert e.value.code == 0
        out = capsys.readouterr().out
        assert "kafka-topic-analyzer" in out and "--backend" in out

    with pytest.raises(SystemExit) as e:
        main(["--version"])
    assert e.value.code == 0
    out = capsys.readouterr().out
    assert "kafka-topic-analyzer-tpu" in out


def _run(capsys, extra):
    argv = [
        "-t", "unit.topic",
        "--source", "synthetic",
        "--synthetic", "partitions=2,messages=500,keys=40,tombstones=200",
        "--batch-size", "256",
        "--quiet",
        "--native", "off",
    ] + extra
    assert main(argv) == 0
    return capsys.readouterr().out


def test_cli_cpu_end_to_end(capsys):
    out = _run(capsys, ["--backend", "cpu", "-c"])
    assert "Topic unit.topic" in out
    assert "Alive keys: " in out
    assert "| P | < OS | > OS | Total |" in out
    # 2 partitions * 500 messages
    assert "Topic Size: " in out
    assert out.count("| 0 |") == 1 and out.count("| 1 |") == 1


def test_cli_tpu_matches_cpu_report(capsys):
    out_cpu = _run(capsys, ["--backend", "cpu", "-c", "--alive-bitmap-bits", "24"])
    out_tpu = _run(capsys, ["--backend", "tpu", "-c", "--alive-bitmap-bits", "24"])

    def stable(s: str) -> str:
        # Drop timing-dependent lines.
        return "\n".join(
            l for l in s.splitlines()
            if not l.startswith(("Scanning took:", "Estimated Msg/s:", "Earliest Message:"))
        )

    # Earliest Message depends on scan start time only when the topic has no
    # older message; the synthetic ts range is in the past, so it is stable —
    # but scan start differs between runs by <1s; keep it excluded anyway.
    assert stable(out_cpu) == stable(out_tpu)


def test_watchdog_degrades_wedged_accelerator_to_cpu(monkeypatch):
    """A wedged device tunnel must degrade to the host CPU platform with a
    warning — never hang the probe's caller."""
    import subprocess

    from kafka_topic_analyzer_tpu import jax_support

    monkeypatch.delenv("KTA_ACCEL_OK", raising=False)
    monkeypatch.delenv("KTA_JAX_PLATFORMS", raising=False)

    def hang(*a, **k):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=k.get("timeout"))

    monkeypatch.setattr(subprocess, "run", hang)
    forced = []
    monkeypatch.setattr(jax_support, "force_platform", forced.append)
    assert jax_support.ensure_responsive_accelerator(timeout_s=1) is False
    assert forced == ["cpu"]


def test_accel_ok_short_circuit_honors_ambient_platform_override():
    """VERDICT r2 weak #1: `KTA_ACCEL_OK=1 JAX_PLATFORMS=cpu kta --backend
    tpu` must complete on the host CPU, never hang.  The wedge mechanism: a
    sitecustomize hook registers the tunnel's backend factory in every
    process and hard-sets jax_platforms to include it, overriding the
    ambient env var; the factory's client init then blocks forever on a
    dead tunnel.  The KTA_ACCEL_OK short-circuit must still drop excluded
    factories (via force_platform) when the ambient override steers away
    from the tunnel."""
    import subprocess
    import sys

    script = """
import os, sys, time
os.environ.pop("KTA_JAX_PLATFORMS", None)
os.environ["KTA_ACCEL_OK"] = "1"        # orchestrator verdict: don't probe
os.environ["JAX_PLATFORMS"] = "cpu"     # the user's steer-away override
import jax
from jax._src import xla_bridge as xb

def wedged_tunnel_factory(*a, **k):     # a wedged client init: blocks forever
    time.sleep(3600)

xb.register_backend_factory("faketunnel", wedged_tunnel_factory, priority=500)
jax.config.update("jax_platforms", "faketunnel,cpu")  # sitecustomize hard-set

from kafka_topic_analyzer_tpu.cli import main
sys.exit(main([
    "-t", "wedge.topic", "--source", "synthetic",
    "--synthetic", "partitions=2,messages=100,keys=10",
    "--batch-size", "64", "--quiet", "--native", "off", "--backend", "tpu",
]))
"""
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "Topic wedge.topic" in proc.stdout


def test_cli_tpu_backend_runs_watchdog(monkeypatch):
    """The user-facing tool must probe the accelerator before backend init
    (VERDICT r1: `kta --backend tpu` hung on a wedged tunnel because only
    bench.py/__graft_entry__ called the watchdog)."""
    import types

    from kafka_topic_analyzer_tpu import jax_support
    from kafka_topic_analyzer_tpu.cli import _make_cli_backend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig

    calls = []
    monkeypatch.setattr(
        jax_support, "ensure_responsive_accelerator",
        lambda *a, **k: calls.append("probe") or True,
    )
    cfg = AnalyzerConfig(num_partitions=1, batch_size=64)
    args = types.SimpleNamespace(backend="tpu")
    _make_cli_backend(args, cfg, (1, 1))
    assert calls == ["probe"]
    args = types.SimpleNamespace(backend="cpu")
    _make_cli_backend(args, cfg, (1, 1))
    assert calls == ["probe"]  # cpu backend never probes


def test_cli_kafka_source_end_to_end(capsys):
    """The reference-identical invocation: -t topic -b broker."""
    from fake_broker import FakeBroker

    records = {
        0: [(i, 1_600_000_000_000 + i, f"k{i%9}".encode(),
             None if i % 5 == 3 else b"x" * 20) for i in range(100)],
        1: [(i, 1_600_000_000_000 + i, None, b"y" * 30) for i in range(60)],
    }
    with FakeBroker("real.topic", records) as broker:
        assert main([
            "-t", "real.topic",
            "-b", f"127.0.0.1:{broker.port}",
            "--librdkafka", "fetch.wait.max.ms=10,check.crcs=true",
            "-c", "--alive-bitmap-bits", "20",
            "--quiet", "--native", "off",
        ]) == 0
    out = capsys.readouterr().out
    assert "Topic real.topic" in out
    assert "Alive keys: " in out
    # 100 + 60 records scanned
    assert "| 0    | 100  | 100   |" in out


def test_cli_json_output(capsys):
    import json

    assert main([
        "-t", "j.topic", "--source", "synthetic",
        "--synthetic", "partitions=2,messages=300,keys=40,tombstones=200",
        "--backend", "tpu", "-c", "--alive-bitmap-bits", "20",
        "--quantiles", "--json", "--quiet", "--native", "off",
    ]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["topic"] == "j.topic"
    assert doc["overall"]["count"] == 600
    assert set(doc["partitions"]) == {"0", "1"}
    row = doc["partitions"]["0"]
    assert row["total"] == 300
    assert row["total"] == row["alive"] + row["tombstones"]
    assert row["end_offset"] == 300
    assert "alive_keys" in doc and "size_quantiles" in doc


def test_cli_json_multi_topic(capsys):
    import json

    assert main([
        "-t", "x,y", "--source", "synthetic",
        "--synthetic", "partitions=1,messages=200,keys=20",
        "--backend", "cpu", "--json", "--quiet", "--native", "off",
    ]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc["topics"]) == {"x", "y"}
    assert doc["union"]["count"] == 400
    assert doc["topics"]["x"]["overall"]["count"] == 200


def test_cli_empty_topic_exits_minus_2(capsys):
    with pytest.raises(SystemExit) as e:
        main([
            "-t", "empty.topic",
            "--source", "synthetic",
            "--synthetic", "partitions=2,messages=0",
            "--quiet", "--native", "off",
        ])
    assert e.value.code == -2
