"""Packed transfer (wire format v4 — the layout contract is packing.py's
module docstring): layout roundtrip, host pre-reductions.

Pinned to ``wire_format=4``: these tests assert the v4 per-record column
layout specifically.  The v5 combiner layout has its own contract suite
(tests/test_wire_v5.py), including v4↔v5 scan byte-identity."""

import jax
import numpy as np
import pytest

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.packing import (
    dedupe_slots_numpy,
    hll_idx_rho_numpy,
    pack_batch,
    packed_nbytes,
    unpack_device,
    unpack_numpy,
)

SPEC = SyntheticSpec(
    num_partitions=5,
    messages_per_partition=300,
    keys_per_partition=40,
    key_null_permille=100,
    tombstone_permille=200,
    seed=21,
)

CFG = AnalyzerConfig(
    num_partitions=5,
    batch_size=512,
    count_alive_keys=True,
    alive_bitmap_bits=18,
    enable_hll=True,
    hll_p=10,
    wire_format=4,
)


def _batch():
    return next(SyntheticSource(SPEC).batches(400)).pad_to(512)


def test_pack_unpack_numpy_roundtrip():
    batch = _batch()
    buf = pack_batch(batch, CFG, use_native=False)
    assert buf.nbytes == packed_nbytes(CFG, 512)
    got = unpack_numpy(buf, CFG)
    assert int(got["n_valid"]) == 400
    assert np.array_equal(got["partition"][:400], batch.partition[:400])
    assert np.array_equal(got["key_len"][:400], batch.key_len[:400])
    assert np.array_equal(got["value_len"][:400], batch.value_len[:400])
    assert np.array_equal(got["key_null"][:400], batch.key_null[:400])
    assert np.array_equal(got["value_null"][:400], batch.value_null[:400])
    assert np.array_equal(got["valid"], batch.valid)
    # v2/v4: ts and size extremes ship as host-reduced per-partition
    # min/max tables (sizes tombstone-excluded, key bytes only when the
    # key is non-null; identities I64_MAX / I64_MIN and I64_MAX / 0).
    sizes = (
        np.where(batch.key_null[:400], 0, batch.key_len[:400]).astype(np.int64)
        + batch.value_len[:400]
    )
    for p in range(CFG.num_partitions):
        sel = batch.partition[:400] == p
        if sel.any():
            assert got["ts_min"][p] == batch.ts_s[:400][sel].min()
            assert got["ts_max"][p] == batch.ts_s[:400][sel].max()
        else:
            assert got["ts_min"][p] == np.iinfo(np.int64).max
            assert got["ts_max"][p] == np.iinfo(np.int64).min
        sized = sel & ~batch.value_null[:400]
        if sized.any():
            assert got["sz_min"][p] == sizes[sized].min()
            assert got["sz_max"][p] == sizes[sized].max()
        else:
            assert got["sz_min"][p] == np.iinfo(np.int64).max
            assert got["sz_max"][p] == 0


def test_device_unpack_matches_numpy_unpack():
    batch = _batch()
    buf = pack_batch(batch, CFG, use_native=False)
    expected = unpack_numpy(buf, CFG)
    got = jax.jit(lambda b: unpack_device(b, CFG))(buf)
    for name, exp in expected.items():
        assert np.array_equal(np.asarray(got[name]), np.asarray(exp)), name


def test_dedupe_numpy_last_writer_wins():
    h32 = np.array([5, 5, 6, 6, 7, 9], dtype=np.uint32)
    active = np.array([1, 1, 1, 1, 1, 0], dtype=bool)
    alive = np.array([1, 0, 0, 1, 1, 1], dtype=bool)
    slots, flags = dedupe_slots_numpy(h32, active, alive, bits=16)
    result = dict(zip(slots.tolist(), flags.tolist()))
    assert result == {5: 0, 6: 1, 7: 1}  # inactive slot 9 ignored


#: HARD-CODED expected HLL wire mode per (per_partition, hll_p) at
#: b=512/P=5 — independent of hll_table_rows, so a threshold bug in the
#: size rule fails here instead of shifting expectations silently.
HLL_MODE = {
    (False, 8): "table",   # 1*256  <= 1536
    (False, 10): "table",  # 1*1024 <= 1536
    (False, 16): "pairs",  # 1*65536 > 1536
    (True, 8): "table",    # 5*256  <= 1536 — the R>1 row-indexed path
    (True, 10): "pairs",   # 5*1024 > 1536
    (True, 16): "pairs",
}


def test_hll_table_rows_size_rule():
    """The one decision function every packer derives the mode from."""
    import dataclasses

    from kafka_topic_analyzer_tpu.packing import hll_table_rows

    for (pp, p), mode in HLL_MODE.items():
        cfg = dataclasses.replace(
            CFG, hll_p=p, distinct_keys_per_partition=pp
        )
        rows = hll_table_rows(cfg, 512)
        assert bool(rows) == (mode == "table"), (pp, p)
        if rows:
            assert rows == (5 if pp else 1)
    # Boundary (global p=8, table = 256 B): 3*86 = 258 >= 256 -> table;
    # 3*85 = 255 < 256 -> pairs.
    cfg = dataclasses.replace(CFG, hll_p=8, distinct_keys_per_partition=False)
    assert hll_table_rows(cfg, 86) == 1
    assert hll_table_rows(cfg, 85) == 0


@pytest.mark.parametrize("hll_p", [8, 10, 16])
@pytest.mark.parametrize("per_partition", [False, True])
def test_native_pack_semantics_match_numpy(hll_p, per_partition):
    import dataclasses

    native = pytest.importorskip("kafka_topic_analyzer_tpu.io.native")
    if not native.native_available():
        pytest.skip("native shim unavailable")
    cfg = dataclasses.replace(
        CFG, hll_p=hll_p, distinct_keys_per_partition=per_partition
    )
    batch = _batch()
    a = pack_batch(batch, cfg, use_native=False)
    b = pack_batch(batch, cfg, use_native=True)
    ua, ub = unpack_numpy(a, cfg), unpack_numpy(b, cfg)
    nv = int(ua["n_valid"])
    assert nv == int(ub["n_valid"])
    hll_names = (
        ("hll_regs",)
        if HLL_MODE[(per_partition, hll_p)] == "table"
        else ("hll_idx", "hll_rho")
    )
    per_record = ("partition", "key_len", "value_len", "key_null",
                  "value_null", "hll_idx", "hll_rho")
    for name in ("partition", "key_len", "value_len", "key_null",
                 "value_null", "ts_min", "ts_max", "sz_min", "sz_max") + hll_names:
        cut = nv if name in per_record else len(ua[name])
        assert np.array_equal(ua[name][:cut], ub[name][:cut]), name
    # Dedupe pair ORDER differs (sorted vs first-touch); counts must match
    # exactly (dict comparison alone would mask duplicate emissions), then
    # compare as dicts.
    na, nb = int(ua["n_pairs"]), int(ub["n_pairs"])
    assert na == nb
    assert dict(zip(ua["alive_slot"][:na].tolist(), ua["alive_flag"][:na].tolist())) \
        == dict(zip(ub["alive_slot"][:nb].tolist(), ub["alive_flag"][:nb].tolist()))


def test_native_pack_odd_batch_size_and_empty():
    """Alignment safety (batch_size not a multiple of 8) and empty batches
    must stay on the native path, not silently fall back or crash."""
    native = pytest.importorskip("kafka_topic_analyzer_tpu.io.native")
    if not native.native_available():
        pytest.skip("native shim unavailable")
    import dataclasses

    odd_cfg = dataclasses.replace(CFG, batch_size=517)
    batch = next(SyntheticSource(SPEC).batches(400)).pad_to(517)
    a = pack_batch(batch, odd_cfg, use_native=False)
    b = native.pack_batch_native(batch, odd_cfg)
    assert b is not None
    ua, ub = unpack_numpy(a, odd_cfg), unpack_numpy(b, odd_cfg)
    for name in ("partition", "key_len", "value_len"):
        assert np.array_equal(ua[name][:400], ub[name][:400]), name
    for name in ("ts_min", "ts_max", "sz_min", "sz_max"):  # [P] tables
        assert np.array_equal(ua[name], ub[name]), name
    from kafka_topic_analyzer_tpu.records import RecordBatch

    empty = native.pack_batch_native(RecordBatch.empty(0), odd_cfg)
    assert empty is not None
    ue = unpack_numpy(empty, odd_cfg)
    assert int(ue["n_valid"]) == 0 and int(ue["n_pairs"]) == 0


def test_pack_rejects_negative_lengths():
    batch = _batch()
    batch.value_len[2] = -5
    with pytest.raises(ValueError, match="negative"):
        pack_batch(batch, CFG, use_native=False)


def test_dedupe_native_matches_numpy():
    native = pytest.importorskip("kafka_topic_analyzer_tpu.io.native")
    if not native.native_available():
        pytest.skip("native shim unavailable")
    rng = np.random.default_rng(3)
    n = 5000
    h32 = rng.integers(0, 2**32, size=n, dtype=np.uint64).astype(np.uint32)
    active = rng.random(n) > 0.1
    alive = rng.random(n) > 0.3
    for bits in (8, 16, 32):
        s_np, f_np = dedupe_slots_numpy(h32, active, alive, bits)
        s_nat, f_nat = native.dedupe_slots_native(h32, active, alive, bits)
        assert dict(zip(s_np.tolist(), f_np.tolist())) == dict(
            zip(s_nat.tolist(), f_nat.tolist())
        ), bits


@pytest.mark.parametrize("p", [10, 16])
def test_hll_idx_rho_matches_reference(p):
    """p=16 is the default AND the u16 edge: max idx 65535 must survive the
    packed section round trip (the old sentinel-bucket design would have
    overflowed here)."""
    from kafka_topic_analyzer_tpu.ops.fnv import splitmix64

    rng = np.random.default_rng(4)
    h64 = rng.integers(0, 2**63, size=1000, dtype=np.uint64)
    # make some values produce long rho runs
    h64[:4] = [0, 1, 1 << 50, (1 << 64) - 1]
    active = np.ones(1000, dtype=bool)
    idx, rho = hll_idx_rho_numpy(h64, active, p)
    for i in range(64):
        h = splitmix64(int(h64[i]))
        exp_idx = h >> (64 - p)
        rest = (h << p) & ((1 << 64) - 1)
        exp_rho = (64 - p + 1) if rest == 0 else (64 - rest.bit_length() + 1)
        assert int(idx[i]) == exp_idx, i
        assert int(rho[i]) == exp_rho, i


def test_pack_rejects_oversize_keys():
    batch = _batch()
    batch.key_len[0] = 1 << 17
    with pytest.raises(ValueError, match="key length"):
        pack_batch(batch, CFG, use_native=False)


def test_pack_rejects_oversize_values_only_for_pallas():
    # The 16 MiB cap exists for the v4 MXU kernel's digit decomposition;
    # the default scatter path accepts full u32 lengths, and under wire v5
    # no per-record value length ever reaches a pallas kernel (the counter
    # fold ships pre-reduced), so only v4+pallas rejects.  (Exercised
    # directly: the synthetic generator can only draw 24-bit lengths.)
    batch = _batch()
    batch.value_len[3] = 1 << 25
    pack_batch(batch, CFG, use_native=False)  # default path: fine
    pallas_cfg = AnalyzerConfig(
        num_partitions=5, batch_size=1024, use_pallas_counters=True,
        wire_format=4,
    )
    with pytest.raises(ValueError, match="value length"):
        pack_batch(batch.pad_to(1024), pallas_cfg, use_native=False)
    v5_cfg = AnalyzerConfig(
        num_partitions=5, batch_size=1024, use_pallas_counters=True,
        wire_format=5,
    )
    pack_batch(batch.pad_to(1024), v5_cfg, use_native=False)  # v5: fine


def test_pack_rejects_non_prefix_valid():
    batch = _batch()
    batch.valid[10] = False
    with pytest.raises(ValueError, match="prefix-valid"):
        pack_batch(batch, CFG, use_native=False)
