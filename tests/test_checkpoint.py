"""Snapshot/resume: an interrupted scan resumed from a snapshot must produce
exactly the same report as an uninterrupted scan (SURVEY.md §5.4)."""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.checkpoint import load_snapshot, save_snapshot
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

SPEC = SyntheticSpec(
    num_partitions=3,
    messages_per_partition=4_000,
    keys_per_partition=200,
    tombstone_permille=150,
    seed=31,
)
CFG = AnalyzerConfig(
    num_partitions=3,
    batch_size=512,
    count_alive_keys=True,
    alive_bitmap_bits=20,
    enable_hll=True,
    hll_p=10,
    enable_quantiles=True,
)


def _metrics_equal(a, b):
    assert np.array_equal(a.per_partition, b.per_partition)
    assert a.alive_keys == b.alive_keys
    assert a.earliest_ts_s == b.earliest_ts_s
    assert a.latest_ts_s == b.latest_ts_s
    assert a.smallest_message == b.smallest_message
    assert a.largest_message == b.largest_message
    assert a.overall_count == b.overall_count
    assert a.distinct_keys_hll == b.distinct_keys_hll
    assert a.quantiles.values == b.quantiles.values


class _Interrupt(Exception):
    pass


class _InterruptingSource(SyntheticSource):
    """Raises after yielding `limit` batches — simulates a crash mid-scan."""

    def __init__(self, spec, limit):
        super().__init__(spec)
        self.limit = limit

    def batches(self, batch_size, partitions=None, start_at=None):
        it = super().batches(batch_size, partitions, start_at)
        for i, b in enumerate(it):
            if start_at is None and i >= self.limit:
                raise _Interrupt()
            yield b


def test_resume_matches_uninterrupted(tmp_path):
    # Uninterrupted run.
    full = run_scan(
        "t", SyntheticSource(SPEC), TpuBackend(CFG, init_now_s=10**10), 512
    ).metrics

    # Interrupted run: snapshot every batch, crash after 7 batches.
    be1 = TpuBackend(CFG, init_now_s=10**10)
    src = _InterruptingSource(SPEC, limit=7)
    with pytest.raises(_Interrupt):
        run_scan(
            "t", src, be1, 512,
            snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
        )

    # Resume with a fresh backend (fresh process semantics).
    be2 = TpuBackend(CFG, init_now_s=0)  # init time restored from snapshot
    result = run_scan(
        "t", SyntheticSource(SPEC), be2, 512,
        snapshot_dir=str(tmp_path), snapshot_every_s=3600.0, resume=True,
    )
    _metrics_equal(full, result.metrics)
    assert be2.init_now_s == 10**10  # restored, not re-stamped


def test_kafka_resume_with_compaction_gaps(tmp_path):
    """Offset-exact resume on a gappy (compacted) offset space."""
    import sys

    sys.path.insert(0, "tests")
    from fake_broker import FakeBroker
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    rows = [
        (off, 1_600_000_000_000 + off, f"k{off % 37}".encode(),
         None if off % 11 == 7 else bytes(20 + off % 64))
        for off in range(0, 900, 3)  # offsets 0,3,6,... (gaps)
    ]
    cfg = AnalyzerConfig(
        num_partitions=1, batch_size=128, count_alive_keys=True,
        alive_bitmap_bits=16,
    )
    with FakeBroker("snap.topic", {0: rows}) as broker:
        bootstrap = f"127.0.0.1:{broker.port}"
        full = run_scan(
            "snap.topic", KafkaWireSource(bootstrap, "snap.topic"),
            TpuBackend(cfg, init_now_s=10**10), 128,
        ).metrics

        # First half: consume 2 batches then stop (limit via islice wrapper).
        src1 = KafkaWireSource(bootstrap, "snap.topic")
        be1 = TpuBackend(cfg, init_now_s=10**10)

        class Half:
            def __getattr__(self, name):
                return getattr(src1, name)

            def batches(self, batch_size, partitions=None, start_at=None):
                it = src1.batches(batch_size, partitions, start_at)
                for i, b in enumerate(it):
                    if i >= 2:
                        raise _Interrupt()
                    yield b

        with pytest.raises(_Interrupt):
            run_scan(
                "snap.topic", Half(), be1, 128,
                snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
            )

        snap = load_snapshot(str(tmp_path), "snap.topic", cfg)
        assert snap is not None
        _, offsets, records_seen, _ = snap
        assert records_seen == 256
        # Offsets have gaps: next offset reflects true positions, not counts.
        assert offsets[0] == rows[255][0] + 1

        be2 = TpuBackend(cfg, init_now_s=0)
        result = run_scan(
            "snap.topic", KafkaWireSource(bootstrap, "snap.topic"), be2, 128,
            snapshot_dir=str(tmp_path), resume=True,
        )
    assert np.array_equal(full.per_partition, result.metrics.per_partition)
    assert full.alive_keys == result.metrics.alive_keys


def test_sharded_resume_matches_uninterrupted(tmp_path):
    """Snapshot/resume through the mesh backend (stacked state leaves)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg = AnalyzerConfig(
        num_partitions=3,
        batch_size=512,
        count_alive_keys=True,
        alive_bitmap_bits=18,
        enable_hll=True,
        hll_p=10,
        mesh_shape=(2, 2),
    )
    full = run_scan(
        "t", SyntheticSource(SPEC), ShardedTpuBackend(cfg, init_now_s=10**10), 512
    ).metrics

    be1 = ShardedTpuBackend(cfg, init_now_s=10**10)
    with pytest.raises(_Interrupt):
        run_scan(
            "t", _InterruptingSource(SPEC, limit=5), be1, 512,
            snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
        )
    be2 = ShardedTpuBackend(cfg, init_now_s=0)
    result = run_scan(
        "t", SyntheticSource(SPEC), be2, 512,
        snapshot_dir=str(tmp_path), resume=True,
    )
    assert np.array_equal(full.per_partition, result.metrics.per_partition)
    assert full.alive_keys == result.metrics.alive_keys
    assert full.distinct_keys_hll == result.metrics.distinct_keys_hll
    assert full.overall_count == result.metrics.overall_count


def test_pack_rejects_out_of_range_partition():
    from kafka_topic_analyzer_tpu.packing import pack_batch
    from kafka_topic_analyzer_tpu.records import RecordBatch

    cfg = AnalyzerConfig(num_partitions=1, batch_size=8)
    b = RecordBatch.empty(4)
    b.valid[:] = True
    b.partition[0] = 40_000
    with pytest.raises(ValueError, match="partition index"):
        pack_batch(b, cfg, use_native=False)


def test_incompatible_snapshot_rejected(tmp_path):
    be = TpuBackend(CFG, init_now_s=5)
    save_snapshot(str(tmp_path), "t", CFG, be.get_state(), {0: 1}, 1, 5)
    other = AnalyzerConfig(num_partitions=4, batch_size=512)
    with pytest.raises(ValueError, match="fingerprint"):
        load_snapshot(str(tmp_path), "t", other)
    with pytest.raises(ValueError, match="fingerprint"):
        load_snapshot(str(tmp_path), "other-topic", CFG)


def test_v3_stamped_single_shard_snapshot_still_loads(tmp_path):
    """r2/r3 stamped EVERY config's fingerprint with state_version=3; S=1
    layouts were identical under v2 and v3, so a v3-stamped S=1 snapshot
    must keep loading after the v2 re-labeling (code-review r4)."""
    import json

    from kafka_topic_analyzer_tpu.checkpoint import (
        SNAPSHOT_NAME,
        _fingerprint_at,
    )

    be = TpuBackend(CFG, init_now_s=5)
    save_snapshot(str(tmp_path), "t", CFG, be.get_state(), {0: 1}, 1, 5)
    # Rewrite the stamp to what the r2/r3 code would have produced.
    path = str(tmp_path / SNAPSHOT_NAME)
    with np.load(path, allow_pickle=False) as z:
        data = {k: z[k] for k in z.files}
    meta = json.loads(str(data["__meta__"]))
    assert CFG.space_shards == 1
    meta["fingerprint"] = _fingerprint_at(CFG, "t", 3)
    data["__meta__"] = np.array(json.dumps(meta))
    np.savez(path.removesuffix(".npz"), **data)
    loaded = load_snapshot(str(tmp_path), "t", CFG)
    assert loaded is not None
