"""Snapshot/resume: an interrupted scan resumed from a snapshot must produce
exactly the same report as an uninterrupted scan (SURVEY.md §5.4)."""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.checkpoint import load_snapshot, save_snapshot
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

SPEC = SyntheticSpec(
    num_partitions=3,
    messages_per_partition=4_000,
    keys_per_partition=200,
    tombstone_permille=150,
    seed=31,
)
CFG = AnalyzerConfig(
    num_partitions=3,
    batch_size=512,
    count_alive_keys=True,
    alive_bitmap_bits=20,
    enable_hll=True,
    hll_p=10,
    enable_quantiles=True,
)


def _metrics_equal(a, b):
    assert np.array_equal(a.per_partition, b.per_partition)
    assert a.alive_keys == b.alive_keys
    assert a.earliest_ts_s == b.earliest_ts_s
    assert a.latest_ts_s == b.latest_ts_s
    assert a.smallest_message == b.smallest_message
    assert a.largest_message == b.largest_message
    assert a.overall_count == b.overall_count
    assert a.distinct_keys_hll == b.distinct_keys_hll
    assert a.quantiles.values == b.quantiles.values


class _Interrupt(Exception):
    pass


class _InterruptingSource(SyntheticSource):
    """Raises after yielding `limit` batches — simulates a crash mid-scan."""

    def __init__(self, spec, limit):
        super().__init__(spec)
        self.limit = limit

    def batches(self, batch_size, partitions=None, start_at=None):
        it = super().batches(batch_size, partitions, start_at)
        for i, b in enumerate(it):
            if start_at is None and i >= self.limit:
                raise _Interrupt()
            yield b


def test_resume_matches_uninterrupted(tmp_path):
    # Uninterrupted run.
    full = run_scan(
        "t", SyntheticSource(SPEC), TpuBackend(CFG, init_now_s=10**10), 512
    ).metrics

    # Interrupted run: snapshot every batch, crash after 7 batches.
    be1 = TpuBackend(CFG, init_now_s=10**10)
    src = _InterruptingSource(SPEC, limit=7)
    with pytest.raises(_Interrupt):
        run_scan(
            "t", src, be1, 512,
            snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
        )

    # Resume with a fresh backend (fresh process semantics).
    be2 = TpuBackend(CFG, init_now_s=0)  # init time restored from snapshot
    result = run_scan(
        "t", SyntheticSource(SPEC), be2, 512,
        snapshot_dir=str(tmp_path), snapshot_every_s=3600.0, resume=True,
    )
    _metrics_equal(full, result.metrics)
    assert be2.init_now_s == 10**10  # restored, not re-stamped


def test_kafka_resume_with_compaction_gaps(tmp_path):
    """Offset-exact resume on a gappy (compacted) offset space."""
    import sys

    sys.path.insert(0, "tests")
    from fake_broker import FakeBroker
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    rows = [
        (off, 1_600_000_000_000 + off, f"k{off % 37}".encode(),
         None if off % 11 == 7 else bytes(20 + off % 64))
        for off in range(0, 900, 3)  # offsets 0,3,6,... (gaps)
    ]
    cfg = AnalyzerConfig(
        num_partitions=1, batch_size=128, count_alive_keys=True,
        alive_bitmap_bits=16,
    )
    with FakeBroker("snap.topic", {0: rows}) as broker:
        bootstrap = f"127.0.0.1:{broker.port}"
        full = run_scan(
            "snap.topic", KafkaWireSource(bootstrap, "snap.topic"),
            TpuBackend(cfg, init_now_s=10**10), 128,
        ).metrics

        # First half: consume 2 batches then stop (limit via islice wrapper).
        src1 = KafkaWireSource(bootstrap, "snap.topic")
        be1 = TpuBackend(cfg, init_now_s=10**10)

        class Half:
            def __getattr__(self, name):
                return getattr(src1, name)

            def batches(self, batch_size, partitions=None, start_at=None):
                it = src1.batches(batch_size, partitions, start_at)
                for i, b in enumerate(it):
                    if i >= 2:
                        raise _Interrupt()
                    yield b

        with pytest.raises(_Interrupt):
            run_scan(
                "snap.topic", Half(), be1, 128,
                snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
            )

        snap = load_snapshot(str(tmp_path), "snap.topic", cfg)
        assert snap is not None
        _, offsets, records_seen, _ = snap
        assert records_seen == 256
        # Offsets have gaps: next offset reflects true positions, not counts.
        assert offsets[0] == rows[255][0] + 1

        be2 = TpuBackend(cfg, init_now_s=0)
        result = run_scan(
            "snap.topic", KafkaWireSource(bootstrap, "snap.topic"), be2, 128,
            snapshot_dir=str(tmp_path), resume=True,
        )
    assert np.array_equal(full.per_partition, result.metrics.per_partition)
    assert full.alive_keys == result.metrics.alive_keys


def test_sharded_resume_matches_uninterrupted(tmp_path):
    """Snapshot/resume through the mesh backend (stacked state leaves)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg = AnalyzerConfig(
        num_partitions=3,
        batch_size=512,
        count_alive_keys=True,
        alive_bitmap_bits=18,
        enable_hll=True,
        hll_p=10,
        mesh_shape=(2, 2),
    )
    full = run_scan(
        "t", SyntheticSource(SPEC), ShardedTpuBackend(cfg, init_now_s=10**10), 512
    ).metrics

    be1 = ShardedTpuBackend(cfg, init_now_s=10**10)
    with pytest.raises(_Interrupt):
        run_scan(
            "t", _InterruptingSource(SPEC, limit=5), be1, 512,
            snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
        )
    be2 = ShardedTpuBackend(cfg, init_now_s=0)
    result = run_scan(
        "t", SyntheticSource(SPEC), be2, 512,
        snapshot_dir=str(tmp_path), resume=True,
    )
    assert np.array_equal(full.per_partition, result.metrics.per_partition)
    assert full.alive_keys == result.metrics.alive_keys
    assert full.distinct_keys_hll == result.metrics.distinct_keys_hll
    assert full.overall_count == result.metrics.overall_count


MESHFREE_SPEC = SyntheticSpec(
    num_partitions=5, messages_per_partition=1500,
    keys_per_partition=31, tombstone_permille=120, seed=3,
)
MESHFREE_BASE = dict(
    num_partitions=5, batch_size=256,
    enable_hll=True, hll_p=10, enable_quantiles=True,
)


def test_cross_mesh_cross_config_resume(tmp_path):
    """Any-config↔any-config resume (DESIGN.md §14): a snapshot taken
    under (mesh 2, workers 2, K 2) resumes under (mesh 4, workers 3, K 4)
    AND under the plain single device, reproducing the uninterrupted
    metrics exactly.  Works because v4 snapshots store the canonical
    mesh-free layout (checkpoint._canonicalize) and redistribute as
    (canonical, identity, ...) rows on load — every fold associative and
    commutative across device rows."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from kafka_topic_analyzer_tpu.config import DispatchConfig
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    full = run_scan(
        "t", SyntheticSource(MESHFREE_SPEC),
        TpuBackend(AnalyzerConfig(**MESHFREE_BASE), init_now_s=10**10), 256,
    ).metrics

    def interrupted(snap_dir):
        be = ShardedTpuBackend(
            AnalyzerConfig(**MESHFREE_BASE, mesh_shape=(2, 1)),
            init_now_s=10**10,
            dispatch=DispatchConfig(superbatch=2, depth=2),
        )
        with pytest.raises(_Interrupt):
            run_scan(
                "t", _InterruptingSource(MESHFREE_SPEC, limit=7), be, 256,
                snapshot_dir=str(snap_dir), snapshot_every_s=0.0,
                ingest_workers=2,
            )

    d1 = tmp_path / "to_mesh4"
    interrupted(d1)
    be2 = ShardedTpuBackend(
        AnalyzerConfig(**MESHFREE_BASE, mesh_shape=(4, 1)),
        init_now_s=0,
        dispatch=DispatchConfig(superbatch=4, depth=1),
    )
    r = run_scan(
        "t", SyntheticSource(MESHFREE_SPEC), be2, 256,
        snapshot_dir=str(d1), resume=True, ingest_workers=3,
    )
    assert r.metrics.to_dict(r.start_offsets, r.end_offsets) == full.to_dict(
        r.start_offsets, r.end_offsets
    )
    assert be2.init_now_s == 10**10  # restored across the mesh change

    d2 = tmp_path / "to_single"
    interrupted(d2)
    be3 = TpuBackend(AnalyzerConfig(**MESHFREE_BASE), init_now_s=0)
    r = run_scan(
        "t", SyntheticSource(MESHFREE_SPEC), be3, 256,
        snapshot_dir=str(d2), resume=True,
    )
    assert r.metrics.to_dict(r.start_offsets, r.end_offsets) == full.to_dict(
        r.start_offsets, r.end_offsets
    )


def test_alive_bitmap_snapshots_stay_mesh_pinned(tmp_path):
    """Alive-key scans keep the mesh in the fingerprint: last-writer-wins
    bit CLEARS only resolve against the row that set the bit, and the
    partition→row assignment changes with the mesh — resuming under a
    different mesh must be a clean error, never a silent miscount."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg2 = AnalyzerConfig(
        num_partitions=3, batch_size=512, count_alive_keys=True,
        alive_bitmap_bits=18, mesh_shape=(2, 1),
    )
    be = ShardedTpuBackend(cfg2, init_now_s=5)
    save_snapshot(str(tmp_path), "t", cfg2, be.get_state(), {0: 1}, 1, 5)
    cfg4 = AnalyzerConfig(
        num_partitions=3, batch_size=512, count_alive_keys=True,
        alive_bitmap_bits=18, mesh_shape=(4, 1),
    )
    be4 = ShardedTpuBackend(cfg4, init_now_s=5)
    # The rejection names the offending feature and the mesh that may
    # resume the snapshot (PR 12's diagnosable-error satellite).
    with pytest.raises(ValueError, match="MESH-PINNED") as ei:
        load_snapshot(str(tmp_path), "t", cfg4, template=be4.get_state())
    assert "--mesh 2,1" in str(ei.value)
    assert "count-alive-keys" in str(ei.value)


def test_scoped_mesh_free_snapshot_canonicalizes_and_distributes(tmp_path):
    """Multi-controller mesh-free snapshots take the same canonical path:
    a PROCESS-LOCAL (scope'd) stacked state folds down at save and
    redistributes into the local stacked template at load — row 0 of this
    process's rows carries exactly its canonical fold, the other rows the
    merge identities.  (The default path for every non-alive multi-host
    resume; exercised here by slicing a single-process mesh state into
    the rows 'process 0' would own.)"""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg = AnalyzerConfig(**MESHFREE_BASE, mesh_shape=(4, 1))
    be = ShardedTpuBackend(cfg, init_now_s=5)
    run_scan("t", SyntheticSource(MESHFREE_SPEC), be, 256)  # non-trivial fold
    host = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)), be.get_state()
    )
    local = jax.tree.map(lambda x: x[:2].copy(), host)  # "process 0" rows
    scope = (0, 2, [0, 1])
    save_snapshot(str(tmp_path), "t", cfg, local, {0: 1}, 1, 5, scope=scope)
    fresh_local = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x))[:2].copy(),
        ShardedTpuBackend(cfg, init_now_s=5).get_state(),
    )
    snap = load_snapshot(
        str(tmp_path), "t", cfg, template=fresh_local, scope=scope
    )
    assert snap is not None
    state = snap[0]
    m = state.metrics
    # Row 0 = the canonical fold of THIS process's saved rows...
    assert np.array_equal(
        m.per_partition[0], host.metrics.per_partition[:2].sum(axis=0)
    )
    assert np.array_equal(
        m.earliest_s[0], host.metrics.earliest_s[:2].min(axis=0)
    )
    # ...and row 1 the merge identities (a fresh state's values).
    assert np.array_equal(m.per_partition[1], np.zeros_like(m.per_partition[1]))
    assert np.array_equal(
        m.earliest_s[1],
        np.full_like(m.earliest_s[1], np.iinfo(np.int64).max),
    )
    assert np.array_equal(
        state.hll.regs[0], host.hll.regs[:2].max(axis=0)
    )
    assert not state.hll.regs[1].any()


def test_mesh_free_snapshot_is_canonical_on_disk(tmp_path):
    """v4 snapshots store the single-device layout regardless of the mesh
    that wrote them — that is WHY any mesh can adopt them."""
    import json as _json

    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    from kafka_topic_analyzer_tpu.checkpoint import (
        SNAPSHOT_NAME,
        config_fingerprint,
    )
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg = AnalyzerConfig(**MESHFREE_BASE, mesh_shape=(2, 1))
    be = ShardedTpuBackend(cfg, init_now_s=5)
    save_snapshot(str(tmp_path), "t", cfg, be.get_state(), {0: 1}, 1, 5)
    with np.load(str(tmp_path / SNAPSHOT_NAME), allow_pickle=False) as z:
        meta = _json.loads(str(z["__meta__"]))
        per_part = z["state.metrics.per_partition"]
        overall = z["state.metrics.overall_count"]
    assert per_part.shape == (5, 7)  # canonical, not [dev, 5, 7]
    assert overall.shape == ()
    # Mesh-free stamp: the single-device config produces the SAME key.
    assert meta["fingerprint"] == config_fingerprint(
        AnalyzerConfig(**MESHFREE_BASE), "t"
    )


def test_pack_rejects_out_of_range_partition():
    from kafka_topic_analyzer_tpu.packing import pack_batch
    from kafka_topic_analyzer_tpu.records import RecordBatch

    cfg = AnalyzerConfig(num_partitions=1, batch_size=8)
    b = RecordBatch.empty(4)
    b.valid[:] = True
    b.partition[0] = 40_000
    with pytest.raises(ValueError, match="partition index"):
        pack_batch(b, cfg, use_native=False)


def test_incompatible_snapshot_rejected(tmp_path):
    be = TpuBackend(CFG, init_now_s=5)
    save_snapshot(str(tmp_path), "t", CFG, be.get_state(), {0: 1}, 1, 5)
    other = AnalyzerConfig(num_partitions=4, batch_size=512)
    with pytest.raises(ValueError, match="fingerprint"):
        load_snapshot(str(tmp_path), "t", other)
    with pytest.raises(ValueError, match="fingerprint"):
        load_snapshot(str(tmp_path), "other-topic", CFG)


def test_v3_stamped_single_shard_snapshot_still_loads(tmp_path):
    """r2/r3 stamped EVERY config's fingerprint with state_version=3; S=1
    layouts were identical under v2 and v3, so a v3-stamped S=1 snapshot
    must keep loading after the v2 re-labeling (code-review r4)."""
    import json

    from kafka_topic_analyzer_tpu.checkpoint import (
        SNAPSHOT_NAME,
        _fingerprint_at,
    )

    be = TpuBackend(CFG, init_now_s=5)
    save_snapshot(str(tmp_path), "t", CFG, be.get_state(), {0: 1}, 1, 5)
    # Rewrite the stamp to what the r2/r3 code would have produced.
    path = str(tmp_path / SNAPSHOT_NAME)
    with np.load(path, allow_pickle=False) as z:
        data = {k: z[k] for k in z.files}
    meta = json.loads(str(data["__meta__"]))
    assert CFG.space_shards == 1
    meta["fingerprint"] = _fingerprint_at(CFG, "t", 3)
    data["__meta__"] = np.array(json.dumps(meta))
    np.savez(path.removesuffix(".npz"), **data)
    loaded = load_snapshot(str(tmp_path), "t", CFG)
    assert loaded is not None
