"""Chaos suite: the live wire scan vs injected transport faults.

Every fault below is armed deterministically (bounded fire counts, chaos
triggered between engine steps) and the scan must complete with metrics
BYTE-IDENTICAL to a fault-free run of the same synthetic topic — recovery
may never drop, duplicate, or reorder a record's contribution.  The last
tests cover the other contract: a partition that stays unreachable past
its retry budget degrades (reported, non-zero exit, resumable snapshot)
instead of aborting the scan.
"""

import json
import os

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

from fake_broker import ChaosTrigger, FakeBroker, FakeCluster, FaultInjector

pytestmark = pytest.mark.chaos

TOPIC = "chaos.topic"

#: Fast recovery pacing so faulted scans stay inside the tier-1 budget.
FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 37}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


RECORDS = {p: _mk_records(p, 400) for p in range(3)}


def _scan_result(bootstrap: str, overrides=None, source=None, batch_size=128):
    src = source or KafkaWireSource(
        bootstrap, TOPIC, overrides=dict(FAST_RETRY, **(overrides or {}))
    )
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=batch_size,
        count_alive_keys=True, alive_bitmap_bits=16,
    )
    backend = CpuExactBackend(cfg, init_now_s=10**10)
    result = run_scan(TOPIC, src, backend, batch_size)
    close = getattr(source, "inner", src)
    close.close()
    return result


def _metrics_doc(result) -> dict:
    return result.metrics.to_dict(result.start_offsets, result.end_offsets)


@pytest.fixture(scope="module")
def baseline():
    """Fault-free run of the synthetic topic — the byte-exact referee."""
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        result = _scan_result(f"127.0.0.1:{broker.port}")
    assert not result.degraded_partitions
    return _metrics_doc(result)


# ---------------------------------------------------------------------------
# faults the scan must absorb with identical metrics


def test_connection_drop_mid_fetch_response(baseline):
    """The leader connection dies after 100 bytes of a fetch response."""
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        trigger = ChaosTrigger(
            src, 2,
            lambda: setattr(
                broker, "faults", FaultInjector().drop_connection(100, times=1)
            ),
        )
        result = _scan_result(None, source=trigger)
    assert not result.degraded_partitions
    assert broker.faults.exhausted()
    assert _metrics_doc(result) == baseline


def test_connection_drop_mid_response_header(baseline):
    """The cut lands inside the 4-byte response length prefix."""
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        trigger = ChaosTrigger(
            src, 1,
            lambda: setattr(
                broker, "faults", FaultInjector().drop_connection(2, times=1)
            ),
        )
        result = _scan_result(None, source=trigger)
    assert not result.degraded_partitions
    assert _metrics_doc(result) == baseline


def test_reconnect_refused_window(baseline):
    """After a drop, the broker refuses the next two reconnects before
    accepting again — the client must back off through the window."""
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        trigger = ChaosTrigger(
            src, 1,
            lambda: setattr(
                broker,
                "faults",
                FaultInjector()
                .drop_connection(0, times=1)
                .refuse_connections(times=2),
            ),
        )
        result = _scan_result(None, source=trigger)
    assert not result.degraded_partitions
    assert broker.faults.exhausted()
    assert _metrics_doc(result) == baseline


def test_stalled_response_past_socket_timeout(baseline):
    """A response hang longer than socket.timeout.ms reads as a dead
    connection; the client reconnects and re-fetches."""
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=60) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}",
            TOPIC,
            overrides=dict(FAST_RETRY, **{"socket.timeout.ms": "250"}),
        )
        trigger = ChaosTrigger(
            src, 1,
            lambda: setattr(
                broker, "faults", FaultInjector().stall_responses(0.7, times=1)
            ),
        )
        result = _scan_result(None, source=trigger)
    assert not result.degraded_partitions
    assert _metrics_doc(result) == baseline


def test_transient_fetch_error_codes(baseline):
    """A few per-partition transient error codes (leader churn style) get
    re-polled, not fatal and not double-counted."""
    faults = FaultInjector().inject_fetch_errors(code=14, times=4)
    with FakeBroker(
        TOPIC, RECORDS, max_records_per_fetch=60, faults=faults
    ) as broker:
        result = _scan_result(f"127.0.0.1:{broker.port}")
    assert not result.degraded_partitions
    assert faults.exhausted()
    assert _metrics_doc(result) == baseline


def test_reload_metadata_swallows_transient_unknown_topic():
    """A restarting broker can answer metadata with UNKNOWN_TOPIC_OR_PARTITION
    before it re-syncs topic state.  At init that is the reference's fatal
    "Topic not found!" exit — but the recovery-path reload already proved
    the topic exists, so it must keep the stale topology instead of letting
    the SystemExit abort the scan."""
    with FakeBroker(TOPIC, RECORDS) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", TOPIC)
        leaders = dict(src._leaders)

        def unknown_topic():
            raise SystemExit("Topic not found!")

        src._load_metadata = unknown_topic
        assert src._reload_metadata() is False
        assert src._leaders == leaders
        src.close()


def test_cluster_broker_death_leader_migration_and_drop(baseline):
    """The acceptance scenario: mid-scan, one FakeCluster node is killed,
    its partition's leadership migrates to the survivor, AND the survivor
    drops a connection mid-response — the scan must still complete with
    metrics byte-identical to the fault-free run."""
    with FakeCluster(
        TOPIC, RECORDS, n_nodes=2, max_records_per_fetch=40
    ) as cluster:
        src = KafkaWireSource(
            cluster.bootstrap, TOPIC, overrides=dict(FAST_RETRY)
        )

        def havoc():
            cluster.nodes[0].faults = FaultInjector().drop_connection(
                7, times=1
            )
            # Node 1 leads partition 1 (p % 2); move it, then kill the node.
            cluster.migrate_leader(1, 0)
            cluster.kill(1)

        result = _scan_result(None, source=ChaosTrigger(src, 2, havoc))
    assert not result.degraded_partitions
    assert _metrics_doc(result) == baseline


def test_leader_migration_between_live_nodes(baseline):
    """Pure leader migration (no death): the old leader NOT_LEADERs the
    fetch, the client reloads metadata and re-routes."""
    with FakeCluster(
        TOPIC, RECORDS, n_nodes=2, max_records_per_fetch=40
    ) as cluster:
        src = KafkaWireSource(
            cluster.bootstrap, TOPIC, overrides=dict(FAST_RETRY)
        )
        trigger = ChaosTrigger(src, 2, lambda: cluster.migrate_leader(1, 0))
        result = _scan_result(None, source=trigger)
    assert not result.degraded_partitions
    assert _metrics_doc(result) == baseline


# ---------------------------------------------------------------------------
# graceful degradation: an unreachable partition must not abort the scan


def test_unreachable_partition_degrades_scan_finishes(baseline):
    """Node 1 dies and leadership never moves: partition 1 exhausts its
    transport retry budget and degrades; partitions 0/2 still finish with
    exact metrics, and the source reports the reason."""
    with FakeCluster(
        TOPIC, RECORDS, n_nodes=2, max_records_per_fetch=40
    ) as cluster:
        src = KafkaWireSource(
            cluster.bootstrap,
            TOPIC,
            overrides=dict(FAST_RETRY, **{"transport.retry.budget": "3"}),
        )
        trigger = ChaosTrigger(src, 1, lambda: cluster.kill(1))
        result = _scan_result(None, source=trigger)
    assert set(result.degraded_partitions) == {1}
    assert "transport failures" in result.degraded_partitions[1]
    for p in ("0", "2"):
        assert _metrics_doc(result)["partitions"][p] == baseline["partitions"][p]


def test_degraded_cli_reports_exits_nonzero_writes_snapshot(tmp_path, capsys):
    """End to end through the CLI: the report flags the degraded partition,
    the process exits non-zero, and a resumable snapshot (stamped with the
    degradation reasons) lands in --snapshot-dir."""
    from kafka_topic_analyzer_tpu import cli

    armed = []

    def arm_on_first_fetch(api_key: int, node_id: int) -> float:
        # The init handshake (metadata + watermarks) must succeed; node 1
        # turns permanently dead only once fetching starts.
        if api_key == kc.API_FETCH and node_id == 1 and not armed:
            armed.append(True)
            cluster.nodes[1].faults = (
                FaultInjector()
                .drop_connection(0, times=10**6)
                .refuse_connections(times=10**6)
            )
        return 0.0

    with FakeCluster(
        TOPIC, RECORDS, n_nodes=2, max_records_per_fetch=100,
        response_delay=arm_on_first_fetch,
    ) as cluster:
        rc = cli.main([
            "-t", TOPIC, "-b", cluster.bootstrap,
            "--backend", "tpu", "--quiet",
            "--snapshot-dir", str(tmp_path),
            "--librdkafka",
            "retry.backoff.ms=5,reconnect.backoff.max.ms=20,"
            "transport.retry.budget=3,socket.timeout.ms=500",
        ])
    assert rc == cli.EXIT_DEGRADED
    out = capsys.readouterr().out
    assert "DEGRADED" in out
    assert "partition 1:" in out
    snap = os.path.join(str(tmp_path), "scan_snapshot.npz")
    assert os.path.exists(snap)
    with np.load(snap, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
    assert "1" in meta["degraded"]
    # Resume offsets for the healthy partitions cover their full range, so
    # a rerun would only re-read the degraded partition's tail.
    assert meta["next_offsets"]["0"] == 400
    assert meta["next_offsets"]["2"] == 400


def test_degraded_json_output(capsys):
    """--json surfaces the degraded map for automation."""
    from kafka_topic_analyzer_tpu import cli

    armed = []

    def arm_on_first_fetch(api_key: int, node_id: int) -> float:
        if api_key == kc.API_FETCH and node_id == 1 and not armed:
            armed.append(True)
            cluster.nodes[1].faults = (
                FaultInjector()
                .drop_connection(0, times=10**6)
                .refuse_connections(times=10**6)
            )
        return 0.0

    with FakeCluster(
        TOPIC, RECORDS, n_nodes=2, max_records_per_fetch=100,
        response_delay=arm_on_first_fetch,
    ) as cluster:
        rc = cli.main([
            "-t", TOPIC, "-b", cluster.bootstrap,
            "--quiet", "--json",
            "--librdkafka",
            "retry.backoff.ms=5,reconnect.backoff.max.ms=20,"
            "transport.retry.budget=3,socket.timeout.ms=500",
        ])
    assert rc == cli.EXIT_DEGRADED
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(doc["degraded_partitions"]) == {"1"}
