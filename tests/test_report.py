"""Report renderer: golden output locked against the reference's format
(src/main.rs:123-179, prettytable-rs default style)."""

import numpy as np

from kafka_topic_analyzer_tpu.report import render_report
from kafka_topic_analyzer_tpu.results import TopicMetrics, U64_MAX
from kafka_topic_analyzer_tpu.utils.table import render_table


def test_render_table_prettytable_style():
    out = render_table([["P", "Tot"], ["0", "12"]])
    assert out == (
        "+---+-----+\n"
        "| P | Tot |\n"
        "+---+-----+\n"
        "| 0 | 12  |\n"
        "+---+-----+\n"
    )


def _metrics() -> TopicMetrics:
    # partition 0: 10 total, 2 tombstones, 8 alive, 1 key_null, 9 key_non_null,
    # key bytes 90, value bytes 800.
    per = np.array([[10, 2, 8, 1, 9, 90, 800]], dtype=np.int64)
    return TopicMetrics(
        partitions=[0],
        per_partition=per,
        earliest_ts_s=0,
        latest_ts_s=1_600_000_000,
        smallest_message=100,
        largest_message=121,
        overall_size=890,
        overall_count=10,
        alive_keys=7,
    )


def test_report_golden():
    out = render_report(
        topic="demo",
        metrics=_metrics(),
        start_offsets={0: 0},
        end_offsets={0: 10},
        duration_secs=2,
        show_alive_keys=True,
    )
    expected = (
        "\n"
        + "=" * 120 + "\n"
        + "Calculating statistics...\n"
        + "Topic demo\n"
        + "Scanning took: 2 seconds\n"
        + "Estimated Msg/s: 5\n"
        + "-" * 120 + "\n"
        + "Earliest Message: 1970-01-01 00:00:00 UTC\n"
        + "Latest Message: 2020-09-13 12:26:40 UTC\n"
        + "-" * 120 + "\n"
        + "Largest Message: 121 bytes\n"
        + "Smallest Message: 100 bytes\n"
        + "Topic Size: 890 bytes\n"
        + "-" * 120 + "\n"
        + "Alive keys: 7\n"
        + "-" * 120 + "\n"
        + "=" * 120 + "\n"
        + "| K = Key, V = Value, P = Partition, Tmb = Tombstone(s), Sz = Size\n"
        + "| DR = Dirty Ratio, A = Average, Lst = last, < OS = start offset, > OS = end offset\n"
        + "+---+------+------+-------+-------+-----+---------+--------+---------+---------+---------+---------+--------+--------+--------+\n"
        + "| P | < OS | > OS | Total | Alive | Tmb | DR      | K Null | K !Null | P-Bytes | K-Bytes | V-Bytes | A K-Sz | A V-Sz | A M-Sz |\n"
        + "+---+------+------+-------+-------+-----+---------+--------+---------+---------+---------+---------+--------+--------+--------+\n"
        + "| 0 | 0    | 10   | 10    | 8     | 2   | 20.0000 | 1      | 9       | 890     | 90      | 800     | 11     | 100    | 111    |\n"
        + "+---+------+------+-------+-------+-----+---------+--------+---------+---------+---------+---------+--------+--------+--------+\n"
        + "\n"
        + "=" * 120 + "\n"
    )
    assert out == expected


def test_derived_metric_semantics():
    m = _metrics()
    # Averages divide by alive (8), floor division (src/metric.rs:132-157).
    assert m.key_size_avg(0) == 90 // 8
    assert m.value_size_avg(0) == 100
    assert m.message_size_avg(0) == 890 // 8
    # Dirty ratio in f32: 2 / (10/100) = 20.0 (src/metric.rs:159-167).
    assert abs(m.dirty_ratio(0) - 20.0) < 1e-6
    # u64::MAX smallest reports as 0 (src/metric.rs:177-183).
    m.smallest_message = U64_MAX
    assert m.smallest_message_reported() == 0


def test_all_keyed_tombstones_partition_renders():
    """A partition retaining only keyed tombstones has size sums > 0 with
    alive == 0.  The reference panics on the divide (src/metric.rs:134-138);
    deliberate divergence: averages report 0 and the report still renders."""
    # 5 total, 5 tombstones, 0 alive, 0 key_null, 5 key_non_null,
    # key bytes 50, value bytes 0.
    per = np.array([[5, 5, 0, 0, 5, 50, 0]], dtype=np.int64)
    m = TopicMetrics(
        partitions=[0],
        per_partition=per,
        earliest_ts_s=0,
        latest_ts_s=1_600_000_000,
        smallest_message=U64_MAX,
        largest_message=0,
        overall_size=50,
        overall_count=5,
        alive_keys=0,
    )
    assert m.key_size_avg(0) == 0
    assert m.value_size_avg(0) == 0
    assert m.message_size_avg(0) == 0
    out = render_report(
        topic="tomb",
        metrics=m,
        start_offsets={0: 0},
        end_offsets={0: 5},
        duration_secs=1,
        show_alive_keys=False,
    )
    assert "| 0 | 0    | 5    |" in out
