"""Default-on bounded race stress for the concurrent ingest machinery
(SURVEY.md §5.2 — the reference is single-threaded by construction; this
build's leader-parallel fetch pool, pipelined send-ahead, and prefetch
threads are its concurrency surface).

Strategy: the same topic served by a 4-node FakeCluster whose per-node
response latency is randomized per run (seeded jitter), so fetch threads
interleave differently every pass — then every pass's metrics must be
byte-identical to the jitter-free single-broker oracle.  A race in chunk
ordering, offset tracking, send-ahead reconciliation, or state folding
shows up as a metrics diff; a deadlock shows up as the suite timeout.

The heavyweight soak stays behind KTA_STRESS (test_utils.py); this tier is
sized to run in every suite pass.
"""

import random

import pytest

from fake_broker import FakeBroker, FakeCluster

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

TOPIC = "race.topic"
P = 8
N_PER_P = 1500


def _records():
    rng = random.Random(0xACE)
    out = {}
    for p in range(P):
        rows = []
        for i in range(N_PER_P):
            key = None if rng.random() < 0.06 else b"k%d-%d" % (p, i % 120)
            value = (
                None
                if key is not None and rng.random() < 0.12
                else bytes(rng.randrange(5, 60))
            )
            rows.append((i, 1_600_000_000_000 + i, key, value))
        out[p] = rows
    return out


RECORDS = _records()


def _scan(bootstrap: str) -> "tuple":
    cfg = AnalyzerConfig(
        num_partitions=P, batch_size=2048, count_alive_keys=True,
        alive_bitmap_bits=20, enable_hll=True, enable_quantiles=True,
    )
    src = KafkaWireSource(bootstrap, TOPIC)
    try:
        m = run_scan(TOPIC, src, CpuExactBackend(cfg), 2048).metrics
    finally:
        src.close()
    return (
        m.overall_count, m.overall_size,
        tuple(m.partitions), m.per_partition.tobytes(),
        m.earliest_ts_s, m.latest_ts_s,
        m.smallest_message, m.largest_message,
        m.alive_keys, round(float(m.distinct_keys_hll or 0), 6),
    )


@pytest.fixture(scope="module")
def oracle():
    with FakeBroker(TOPIC, RECORDS, max_records_per_fetch=200) as broker:
        yield _scan(f"127.0.0.1:{broker.port}")


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_jittered_cluster_matches_oracle(oracle, seed):
    rng = random.Random(seed)
    # Per-node base skew + per-response jitter: leaders answer in a
    # different order every round, so pipelined send-aheads and the fetch
    # pool's phase-2 bookkeeping interleave differently each pass.
    base = {node: rng.uniform(0, 0.004) for node in range(4)}

    def delay(api_key, node_id):
        return base[node_id] + rng.uniform(0, 0.004)

    with FakeCluster(
        TOPIC, RECORDS, n_nodes=4, max_records_per_fetch=90,
        response_delay=delay,
    ) as cluster:
        got = _scan(cluster.bootstrap)
    assert got == oracle


def test_jittered_cluster_matches_oracle_native_off(oracle):
    """Same interleave stress through the pure-Python decode path (the
    native fast path short-circuits parts of the per-frame loop)."""
    rng = random.Random(99)

    def delay(api_key, node_id):
        return rng.uniform(0, 0.003)

    with FakeCluster(
        TOPIC, RECORDS, n_nodes=4, max_records_per_fetch=90,
        response_delay=delay,
    ) as cluster:
        cfg = AnalyzerConfig(
            num_partitions=P, batch_size=2048, count_alive_keys=True,
            alive_bitmap_bits=20, enable_hll=True, enable_quantiles=True,
        )
        src = KafkaWireSource(
            cluster.bootstrap, TOPIC, use_native_hashing=False
        )
        try:
            m = run_scan(TOPIC, src, CpuExactBackend(cfg), 2048).metrics
        finally:
            src.close()
    assert (m.overall_count, m.alive_keys) == (oracle[0], oracle[8])
    assert m.per_partition.tobytes() == oracle[3]
