"""Integration: the scan pipeline's telemetry outputs.

Covers the ISSUE acceptance criteria end to end:
- a chaos-suite scan (fake_broker fault injection) exposes non-zero
  retry/eviction/degraded counters via the live Prometheus endpoint, with
  matching JSONL events;
- the --trace-json output is valid Chrome trace-event JSON whose
  per-stage span totals agree with ScanProfile stage seconds within 5%;
- the engine's registry counters agree with the scan result, and the
  final heartbeat drains the per-partition lag gauges to zero.

The default registry is process-global, so every test starts from a
reset() (registrations survive; values zero)."""

import json
import re
import urllib.request

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter
from kafka_topic_analyzer_tpu.obs.registry import default_registry
from kafka_topic_analyzer_tpu.obs.trace import SpanTracer

from fake_broker import ChaosTrigger, FakeBroker, FaultInjector

TOPIC = "telemetry.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}


@pytest.fixture(autouse=True)
def _reset_registry():
    default_registry().reset()
    yield
    default_registry().reset()


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 37}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


def _synthetic_scan(tracer=None, **kwargs):
    spec = SyntheticSpec(
        num_partitions=2, messages_per_partition=400, keys_per_partition=50
    )
    cfg = AnalyzerConfig(num_partitions=2, batch_size=128)
    return run_scan(
        "synth",
        SyntheticSource(spec),
        CpuExactBackend(cfg, init_now_s=10**10),
        128,
        tracer=tracer,
        **kwargs,
    )


def _scrape(port: int) -> str:
    url = f"http://127.0.0.1:{port}/metrics"
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.read().decode()


def _sample(text: str, name: str) -> float:
    m = re.search(rf"^{re.escape(name)}(?:{{[^}}]*}})? (\S+)$", text, re.M)
    assert m, f"{name} missing from exposition:\n{text}"
    return float(m.group(1))


# ---------------------------------------------------------------------------
# engine counters + heartbeat gauges


def test_engine_counters_match_scan_result():
    result = _synthetic_scan()
    assert obs_metrics.SCAN_RECORDS.value == result.metrics.overall_count
    assert obs_metrics.SCAN_BATCHES.value > 0
    hist = obs_metrics.BATCH_RECORDS.samples()[0]
    assert hist["count"] == obs_metrics.SCAN_BATCHES.value
    assert hist["sum"] == result.metrics.overall_count
    # The forced closing heartbeat reports drained partitions: zero lag.
    for s in obs_metrics.PARTITION_LAG.samples():
        assert s["value"] == 0.0
    # Step/finalize latency histograms saw every dispatch.
    assert (
        obs_metrics.BACKEND_STEP_SECONDS.samples()[0]["count"]
        == obs_metrics.SCAN_BATCHES.value
    )
    assert obs_metrics.BACKEND_FINALIZE_SECONDS.samples()[0]["count"] == 1


def test_scan_result_carries_merged_telemetry():
    result = _synthetic_scan()
    tel = result.telemetry
    assert tel is not None
    assert (
        tel["kta_scan_records_total"]["samples"][0]["value"]
        == result.metrics.overall_count
    )
    stages = {
        s["labels"]["stage"]
        for s in tel["kta_stage_seconds_total"]["samples"]
    }
    assert {"ingest", "dispatch", "finalize"} <= stages
    json.dumps(tel)  # the --json telemetry block must be JSON-able


def test_scan_lifecycle_events():
    seen = []
    sink = lambda etype, fields: seen.append((etype, fields))  # noqa: E731
    obs_events.add_sink(sink)
    try:
        result = _synthetic_scan()
    finally:
        obs_events.remove_sink(sink)
    types = [t for t, _ in seen]
    assert types[0] == "scan_start"
    assert types[-1] == "scan_end"
    assert "heartbeat" in types
    end = dict(seen[-1][1])
    assert end["records"] == result.metrics.overall_count
    assert end["degraded"] == 0


# ---------------------------------------------------------------------------
# trace spans vs ScanProfile


def test_trace_json_valid_and_agrees_with_profile(tmp_path):
    tracer = SpanTracer()
    result = _synthetic_scan(tracer=tracer)
    path = tmp_path / "trace.json"
    tracer.write(str(path))
    doc = json.loads(path.read_text())
    evs = doc["traceEvents"]
    assert evs, "trace must carry events"
    for ev in evs:
        assert ev["ph"] == "X"
        assert set(ev) >= {"name", "cat", "ts", "dur", "pid", "tid"}
    span_totals: "dict[str, float]" = {}
    for ev in evs:
        if ev["cat"] == "stage":
            span_totals[ev["name"]] = (
                span_totals.get(ev["name"], 0.0) + ev["dur"] / 1e6
            )
    for name, st in result.profile.stages.items():
        assert span_totals[name] == pytest.approx(st.seconds, rel=0.05), (
            f"stage {name}: trace says {span_totals[name]}, "
            f"profile says {st.seconds}"
        )


# ---------------------------------------------------------------------------
# chaos: fault counters via the live scrape endpoint + matching events


@pytest.mark.chaos
def test_chaos_scan_exposes_fault_counters(tmp_path):
    records = {p: _mk_records(p, 400) for p in range(2)}
    events_path = tmp_path / "events.jsonl"
    sink = obs_events.JsonlEventLog(str(events_path))
    obs_events.add_sink(sink)
    exporter = PrometheusExporter(0)
    try:
        with FakeBroker(TOPIC, records, max_records_per_fetch=60) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
            )
            cfg = AnalyzerConfig(num_partitions=2, batch_size=128)
            # Arm after the first batch (init handshake must succeed): the
            # next fetch round hits a dropped connection, then a refused
            # reconnect — transport failure, eviction, backoff, recovery.
            trigger = ChaosTrigger(
                src, 1,
                lambda: setattr(
                    broker, "faults",
                    FaultInjector()
                    .drop_connection(100, times=1)
                    .refuse_connections(times=1),
                ),
            )
            result = run_scan(
                TOPIC, trigger, CpuExactBackend(cfg, init_now_s=10**10), 128
            )
            src.close()
            assert broker.faults.exhausted()
        assert not result.degraded_partitions
        assert result.metrics.overall_count == 800

        text = _scrape(exporter.port)
        assert _sample(text, "kta_transport_failures_total") >= 1
        assert _sample(text, "kta_connection_evictions_total") >= 1
        assert _sample(text, "kta_backoff_sleeps_total") >= 1
        assert _sample(text, "kta_scan_records_total") == 800
        assert _sample(text, "kta_fetch_requests_total") >= 1
        assert _sample(text, "kta_scan_degraded_partitions") == 0
    finally:
        exporter.close()
        obs_events.remove_sink(sink)
        sink.close()

    docs = [json.loads(l) for l in events_path.read_text().splitlines()]
    by_type: "dict[str, list[dict]]" = {}
    for d in docs:
        by_type.setdefault(d["type"], []).append(d)
    assert "scan_start" in by_type and "scan_end" in by_type
    # The JSONL log and the registry tell the same fault story.
    assert len(by_type["transport_failure"]) >= 1
    assert len(by_type["connection_evicted"]) >= 1
    assert by_type["scan_end"][0]["degraded"] == 0


@pytest.mark.chaos
def test_degraded_scan_books_budget_exhaustion(tmp_path):
    records = {p: _mk_records(p, 200) for p in range(2)}
    events_path = tmp_path / "events.jsonl"
    sink = obs_events.JsonlEventLog(str(events_path))
    obs_events.add_sink(sink)
    try:
        with FakeBroker(TOPIC, records, max_records_per_fetch=60) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}",
                TOPIC,
                overrides=dict(FAST_RETRY, **{"transport.retry.budget": "2"}),
            )
            cfg = AnalyzerConfig(num_partitions=2, batch_size=128)
            # Permanently dead past the first batch: both partitions
            # exhaust their budget and degrade (scan still returns).
            trigger = ChaosTrigger(
                src, 1,
                lambda: setattr(
                    broker, "faults",
                    FaultInjector()
                    .drop_connection(0, times=10**6)
                    .refuse_connections(times=10**6),
                ),
            )
            result = run_scan(
                TOPIC, trigger, CpuExactBackend(cfg, init_now_s=10**10), 128
            )
            src.close()
        assert set(result.degraded_partitions) == {0, 1}
        assert obs_metrics.RETRY_BUDGET_EXHAUSTIONS.value == 2
        assert obs_metrics.DEGRADED_PARTITIONS.value == 2
        tel = result.telemetry
        assert (
            tel["kta_retry_budget_exhaustions_total"]["samples"][0]["value"]
            == 2
        )
    finally:
        obs_events.remove_sink(sink)
        sink.close()
    types = [json.loads(l)["type"] for l in events_path.read_text().splitlines()]
    assert types.count("retry_budget_exhausted") == 2
    assert types.count("partition_degraded") == 2


# ---------------------------------------------------------------------------
# CLI flags end to end


def test_cli_telemetry_flags(tmp_path, capsys):
    from kafka_topic_analyzer_tpu import cli

    events_path = tmp_path / "events.jsonl"
    trace_path = tmp_path / "trace.json"
    rc = cli.main([
        "-t", "cli.topic",
        "--source", "synthetic",
        "--synthetic", "partitions=2,messages=300",
        "--quiet", "--json", "--stats",
        "--events-jsonl", str(events_path),
        "--trace-json", str(trace_path),
    ])
    assert rc == 0
    captured = capsys.readouterr()
    doc = json.loads(captured.out.splitlines()[-1])
    assert (
        doc["telemetry"]["kta_scan_records_total"]["samples"][0]["value"]
        == 600
    )
    assert "telemetry:" in captured.err  # --stats digest
    assert "scan stages:" in captured.err
    types = [json.loads(l)["type"] for l in events_path.read_text().splitlines()]
    assert types[0] == "scan_start" and types[-1] == "scan_end"
    trace_doc = json.loads(trace_path.read_text())
    names = {e["name"] for e in trace_doc["traceEvents"]}
    assert {"ingest", "dispatch"} <= names
