"""Segment-dump roundtrip: write from a synthetic topic, re-scan, same report.

Plus the cold-path surface: the catalog/store layer, zero-copy reads,
corrupt-segment classification, and the parallel segment scan's
byte-identity against the sequential wire scan of the same data
(``--ingest-workers N`` x ``--superbatch K`` sweep).
"""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.segfile import (
    CorruptSegmentError,
    MalformedSegmentError,
    SegmentFile,
    SegmentFileSource,
    TruncatedSegmentError,
    write_segment_from_batches,
)
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

pytestmark = pytest.mark.segfile

SPEC = SyntheticSpec(
    num_partitions=3,
    messages_per_partition=2_500,
    keys_per_partition=100,
    tombstone_permille=120,
    seed=5,
)


@pytest.fixture()
def seg_dir(tmp_path):
    src = SyntheticSource(SPEC)
    for p in src.partitions():
        write_segment_from_batches(
            str(tmp_path), "t", p, list(src.batches(1000, partitions=[p]))
        )
    return str(tmp_path)


def test_roundtrip_header_and_watermarks(seg_dir):
    src = SegmentFileSource(seg_dir, "t")
    assert src.partitions() == [0, 1, 2]
    start, end = src.watermarks()
    assert start == {0: 0, 1: 0, 2: 0}
    assert end == {0: 2500, 1: 2500, 2: 2500}
    seg = SegmentFile(f"{seg_dir}/t-0.ktaseg")
    assert seg.count == 2500 and seg.partition == 0


def test_segfile_scan_matches_synthetic_scan(seg_dir):
    cfg = AnalyzerConfig(num_partitions=3, batch_size=777, count_alive_keys=True,
                         alive_bitmap_bits=20)
    m_synth = run_scan(
        "t", SyntheticSource(SPEC), CpuExactBackend(cfg, init_now_s=10**10), 777
    ).metrics
    m_seg = run_scan(
        "t", SegmentFileSource(seg_dir, "t"), CpuExactBackend(cfg, init_now_s=10**10), 777
    ).metrics
    assert np.array_equal(m_synth.per_partition, m_seg.per_partition)
    assert m_synth.alive_keys == m_seg.alive_keys
    assert m_synth.earliest_ts_s == m_seg.earliest_ts_s
    assert m_synth.latest_ts_s == m_seg.latest_ts_s
    assert m_synth.smallest_message == m_seg.smallest_message
    assert m_synth.largest_message == m_seg.largest_message


def test_topic_name_prefix_not_confused(seg_dir):
    # A topic whose name extends the requested one must not be swallowed
    # by filename matching.
    src0 = SyntheticSource(SPEC)
    write_segment_from_batches(
        seg_dir, "t-extra", 0, list(src0.batches(1000, partitions=[0]))
    )
    src = SegmentFileSource(seg_dir, "t")
    assert src.partitions() == [0, 1, 2]
    _, end = src.watermarks()
    assert end[0] == 2500  # not the t-extra file's data


def test_dump_writer_roundtrip_with_gappy_offsets(tmp_path):
    """Dump a gappy (compacted) stream in rolled chunks, re-read it, and
    get identical metrics plus offset-exact watermarks."""
    from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter, TeeSource
    from kafka_topic_analyzer_tpu.io.kafka_wire import records_to_batch

    rows = []
    for off in range(0, 600, 3):  # offsets with gaps
        rows.append((0, 1_600_000_000_000 + off, f"k{off % 13}".encode(),
                     None if off % 7 == 0 else bytes(10 + off % 40)))
    batch = records_to_batch(rows)
    batch.offsets = np.arange(0, 600, 3, dtype=np.int64)

    # Append in 50-record batches; chunks roll once >= 64 records buffered
    # (rolling is batch-granular).
    writer = SegmentDumpWriter(str(tmp_path), "gap", records_per_chunk=64)
    for lo in range(0, 200, 50):
        writer.append(batch.take(np.arange(lo, lo + 50)))
    writer.close()

    src = SegmentFileSource(str(tmp_path), "gap")
    start, end = src.watermarks()
    assert start == {0: 0}
    assert end == {0: 598}  # last retained offset 597 + 1
    from kafka_topic_analyzer_tpu.records import RecordBatch

    full = RecordBatch.concat(list(src.batches(50)))
    assert len(full) == 200
    assert np.array_equal(full.offsets, batch.offsets)
    assert np.array_equal(full.key_len, batch.key_len)
    assert np.array_equal(full.value_null, batch.value_null)
    # Chunked files actually rolled.
    import os

    chunks = [f for f in os.listdir(tmp_path) if f.startswith("gap-0.c")]
    assert len(chunks) == 2  # rolled at 100 records (2 x 50-record appends)

    # Offset-exact resume mid-chunk.
    resumed = RecordBatch.concat(list(src.batches(50, start_at={0: 301})))
    assert int(resumed.offsets[0]) == 303  # first retained offset >= 301


def test_dump_preserves_nonzero_start_of_gapless_source(tmp_path):
    """Re-dumping an offset-less source that starts above 0 (retention) must
    keep the true start offset, not rebase to 0."""
    from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter, TeeSource

    src_dir = tmp_path / "src"
    dst_dir = tmp_path / "dst"
    src_dir.mkdir()
    src = SyntheticSource(SPEC)
    write_segment_from_batches(
        str(src_dir), "t", 0, list(src.batches(1000, partitions=[0])),
        start_offset=1000,
    )
    reader = SegmentFileSource(str(src_dir), "t")
    assert reader.watermarks()[0] == {0: 1000}
    tee = TeeSource(reader, SegmentDumpWriter(str(dst_dir), "t"))
    for _ in tee.batches(700):
        pass
    tee.close()
    redump = SegmentFileSource(str(dst_dir), "t")
    start, end = redump.watermarks()
    assert start == {0: 1000}
    assert end == {0: 1000 + SPEC.messages_per_partition}


def test_corrupt_magic_rejected(seg_dir, tmp_path):
    bad = tmp_path / "t-9.ktaseg"
    data = bytearray(open(f"{seg_dir}/t-0.ktaseg", "rb").read())
    data[:8] = b"NOTASEG!"
    bad.write_bytes(bytes(data))
    # Classified (CorruptFrameError taxonomy) AND still a ValueError for
    # pre-classification callers.
    with pytest.raises(MalformedSegmentError, match="bad magic") as e:
        SegmentFile(str(bad))
    assert isinstance(e.value, ValueError)
    assert e.value.kind == "malformed-header"
    assert e.value.path == str(bad)
    assert e.value.span == (0, 8)


# ---------------------------------------------------------------------------
# corrupt-segment classification (decode-surface rule: tools/lint.sh)


def test_truncated_header_classified(tmp_path):
    bad = tmp_path / "t-0.ktaseg"
    bad.write_bytes(b"KTASEG01\x00\x00")  # 10 of 28 header bytes
    with pytest.raises(TruncatedSegmentError, match="truncated header") as e:
        SegmentFile(str(bad))
    assert e.value.kind == "truncated"
    assert e.value.path == str(bad)
    from kafka_topic_analyzer_tpu.io.kafka_codec import CorruptFrameError

    assert isinstance(e.value, CorruptFrameError)


def test_truncated_payload_classified(seg_dir, tmp_path):
    data = open(f"{seg_dir}/t-0.ktaseg", "rb").read()
    bad = tmp_path / "t-0.ktaseg"
    bad.write_bytes(data[:-100])  # column payload cut short
    with pytest.raises(TruncatedSegmentError, match="size") as e:
        SegmentFile(str(bad))
    assert e.value.kind == "truncated"
    assert e.value.partition == 0
    assert e.value.num_records == 2500
    # Trailing garbage is malformed, not truncated.
    bad.write_bytes(data + b"xx")
    with pytest.raises(MalformedSegmentError, match="size"):
        SegmentFile(str(bad))


def test_impossible_header_classified(tmp_path):
    import struct

    from kafka_topic_analyzer_tpu.io.segfile import _HEADER

    bad = tmp_path / "t-0.ktaseg"
    bad.write_bytes(_HEADER.pack(b"KTASEG01", 0, 0, 0, -5))
    with pytest.raises(MalformedSegmentError, match="impossible header"):
        SegmentFile(str(bad))


def test_filename_header_mismatch_classified(seg_dir, tmp_path):
    import shutil

    shutil.copy(f"{seg_dir}/t-0.ktaseg", tmp_path / "t-7.ktaseg")
    with pytest.raises(MalformedSegmentError, match="does not match filename"):
        SegmentFileSource(str(tmp_path), "t")


def test_overlapping_chunks_classified(seg_dir, tmp_path):
    import shutil

    # Two copies of the same chunk under rolled-chunk names: identical
    # [0, 2500) offset ranges overlap.
    shutil.copy(f"{seg_dir}/t-0.ktaseg", tmp_path / "t-0.c0.ktaseg")
    shutil.copy(f"{seg_dir}/t-0.ktaseg", tmp_path / "t-0.c1.ktaseg")
    with pytest.raises(MalformedSegmentError, match="overlapping"):
        SegmentFileSource(str(tmp_path), "t")


# ---------------------------------------------------------------------------
# catalog/store layer (io/segstore.py)


def test_open_segment_store_and_catalog(seg_dir):
    from kafka_topic_analyzer_tpu.io.segstore import (
        DirectorySegmentStore,
        SegmentCatalog,
        open_segment_store,
    )

    store = open_segment_store(seg_dir)
    assert isinstance(store, DirectorySegmentStore)
    refs = store.list_refs("t")
    assert [r.partition for r in refs] == [0, 1, 2]
    assert all(r.size > 0 for r in refs)
    catalog = SegmentCatalog(store, "t")
    assert catalog.num_files == 3
    assert catalog.total_bytes == sum(r.size for r in refs)
    assert catalog.record_counts() == {0: 2500, 1: 2500, 2: 2500}
    # The source built from a plain path routes through the same store.
    src = SegmentFileSource(seg_dir, "t")
    assert src.partition_record_counts() == {0: 2500, 1: 2500, 2: 2500}


def test_open_segment_store_rejects_unknown_scheme(tmp_path):
    from kafka_topic_analyzer_tpu.io.segstore import open_segment_store

    # Unknown schemes list what IS supported (s3://-style specs route to
    # the remote tier now — tests/test_objstore.py).
    with pytest.raises(ValueError, match="scheme 'gs' is not supported"):
        open_segment_store("gs://bucket/prefix")
    with pytest.raises(ValueError, match="not a directory"):
        open_segment_store(str(tmp_path / "missing"))
    # file:// is the explicit spelling of the local store.
    store = open_segment_store(f"file://{tmp_path}")
    assert store.list_refs("t") == []


def test_segment_telemetry_counters(seg_dir):
    from kafka_topic_analyzer_tpu.obs.registry import default_registry
    from kafka_topic_analyzer_tpu.results import SegmentStats

    before = SegmentStats.from_telemetry(default_registry().snapshot())
    src = SegmentFileSource(seg_dir, "t")
    n = sum(len(b) for b in src.batches(1000))
    after = SegmentStats.from_telemetry(default_registry().snapshot())
    assert after.files - before.files == 3
    assert after.records - before.records == n == 7500
    assert after.batches - before.batches == 9  # ceil(2500/1000) x 3
    assert after.bytes_mapped - before.bytes_mapped == src.catalog.total_bytes
    assert after.as_dict()["files"] == after.files


# ---------------------------------------------------------------------------
# zero-copy read + pack


def test_read_batch_is_zero_copy_and_matches_copy(seg_dir):
    seg = SegmentFile(f"{seg_dir}/t-0.ktaseg")
    view = seg.read_batch(100, 400)
    deep = seg.read_batch(100, 400, copy=True)
    for name, _ in view.FIELDS:
        assert np.array_equal(getattr(view, name), getattr(deep, name)), name
    # The int/hash columns and null flags alias the file mapping...
    for name in ("key_len", "value_len", "key_null", "value_null",
                 "key_hash32", "key_hash64"):
        assert np.shares_memory(getattr(view, name), seg._mm), name
        assert not getattr(view, name).flags.writeable, name
    # ...partition/valid alias the per-file constants (one allocation per
    # file, not per batch), and the copy path detaches everything.
    assert np.shares_memory(view.partition, seg._const_partition)
    assert np.shares_memory(view.valid, seg._const_valid)
    for name, _ in deep.FIELDS:
        assert not np.shares_memory(getattr(deep, name), seg._mm), name


def test_pack_from_memmap_views_matches_copy_pack(seg_dir):
    """wire-v4 rows built straight from mapped columns (the cold path's
    pack) must be byte-identical to packing detached copies, with and
    without the native shim, including the pack_batch(out=) row path."""
    from kafka_topic_analyzer_tpu.packing import pack_batch, packed_nbytes

    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=512, count_alive_keys=True,
        alive_bitmap_bits=16, enable_hll=True, hll_p=8,
        enable_quantiles=True,
    )
    seg = SegmentFile(f"{seg_dir}/t-1.ktaseg")
    view = seg.read_batch(0, 512)
    view.partition = np.full(512, 1, dtype=np.int32)  # dense remap rebinding
    deep = view.copy()
    for use_native in (False, True):
        a = pack_batch(view, cfg, use_native=use_native)
        b = pack_batch(deep, cfg, use_native=use_native)
        assert np.array_equal(a, b)
        row = np.empty(packed_nbytes(cfg, 512), dtype=np.uint8)
        assert pack_batch(view, cfg, use_native=use_native, out=row) is row
        assert np.array_equal(row, a)


# ---------------------------------------------------------------------------
# parallel cold scan


def test_shard_partitions_weighted_balances_and_stays_disjoint():
    from kafka_topic_analyzer_tpu.parallel.ingest import shard_partitions

    weights = {0: 1000, 1: 10, 2: 10, 3: 10}
    groups = shard_partitions([0, 1, 2, 3], 2, weights=weights)
    assert groups == [[0], [1, 2, 3]]  # greedy LPT: hot partition isolated
    # Disjoint cover, deterministic, empty groups dropped.
    flat = sorted(p for g in groups for p in g)
    assert flat == [0, 1, 2, 3]
    assert shard_partitions([0, 1, 2, 3], 2, weights=weights) == groups
    assert shard_partitions([5], 4, weights={5: 9}) == [[5]]
    # No weights: unchanged mesh round-robin rule.
    assert shard_partitions([0, 1, 2, 3], 2) == [[0, 2], [1, 3]]


def test_parallel_segfile_scan_matches_sequential(seg_dir):
    cfg = AnalyzerConfig(num_partitions=3, batch_size=777,
                         count_alive_keys=True, alive_bitmap_bits=20)

    def scan(workers):
        return run_scan(
            "t", SegmentFileSource(seg_dir, "t"),
            CpuExactBackend(cfg, init_now_s=10**10), 777,
            ingest_workers=workers,
        )

    ref = scan(1)
    for n in (2, 3):
        got = scan(n)
        assert got.ingest_workers == n
        assert np.array_equal(
            ref.metrics.per_partition, got.metrics.per_partition
        )
        assert ref.metrics.to_dict() == got.metrics.to_dict()
        assert got.start_offsets == ref.start_offsets
        assert got.end_offsets == ref.end_offsets


def test_wire_dump_rescan_byte_identity_workers_x_superbatch(tmp_path):
    """The cold-path acceptance bar: produce → wire scan with a
    --dump-segments tee → re-scan the dump from disk, swept across ingest
    workers N∈{1,2,4} × superbatch K∈{1,4} — every cold scan's report doc
    must be byte-identical to the sequential wire scan's (same metrics,
    same watermarks), with a deliberately skewed partition layout so the
    weighted worker sharding is exercised."""
    from fake_broker import FakeBroker

    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import DispatchConfig
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
    from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter, TeeSource

    def mk(partition, n):
        return [
            (
                i,
                1_600_000_000_000 + i * 1000,
                f"k{partition}-{i % 23}".encode() if i % 5 else None,
                bytes(20 + (i % 13)) if i % 7 else None,
            )
            for i in range(n)
        ]

    records = {0: mk(0, 240), 1: mk(1, 120), 2: mk(2, 60)}
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=64, count_alive_keys=True,
        alive_bitmap_bits=16, enable_hll=True, hll_p=8,
        enable_quantiles=True,
    )
    seg_dir = str(tmp_path / "dump")

    def doc(result):
        d = result.metrics.to_dict(result.start_offsets, result.end_offsets)
        d["start"] = result.start_offsets
        d["end"] = result.end_offsets
        return d

    with FakeBroker("cold.topic", records, max_records_per_fetch=50) as broker:
        src = TeeSource(
            KafkaWireSource(f"127.0.0.1:{broker.port}", "cold.topic"),
            SegmentDumpWriter(seg_dir, "cold.topic", records_per_chunk=100),
        )
        ref = doc(run_scan(
            "cold.topic", src, TpuBackend(cfg, init_now_s=10**10), 64
        ))
        src.close()

    for workers in (1, 2, 4):
        for k in (1, 4):
            backend = TpuBackend(
                cfg, init_now_s=10**10,
                dispatch=DispatchConfig(superbatch=k),
            )
            result = run_scan(
                "cold.topic", SegmentFileSource(seg_dir, "cold.topic"),
                backend, 64, ingest_workers=workers,
            )
            assert result.ingest_workers == min(workers, 3)
            assert result.superbatch_k == k
            assert doc(result) == ref, (workers, k)


@pytest.mark.parametrize("workers", ["1", "4"])
def test_cli_segfile_parallel_scan_with_digest(seg_dir, capsys, workers):
    """End-to-end cold path through the CLI: --source segfile with parallel
    ingest workers, the --json segments digest, and the telemetry block's
    kta_segment_* counters."""
    import json

    from kafka_topic_analyzer_tpu.cli import main
    from kafka_topic_analyzer_tpu.obs.registry import default_registry
    from kafka_topic_analyzer_tpu.results import SegmentStats

    # The default registry is process-global and cumulative, so under
    # pytest (many scans, one process) the digest carries prior tests'
    # counters too — assert the delta this scan added.  A real CLI process
    # starts from zero.
    before = SegmentStats.from_telemetry(default_registry().snapshot())
    assert main([
        "-t", "t", "--source", "segfile", "--segment-dir", seg_dir,
        "--backend", "cpu", "-c", "--alive-bitmap-bits", "20",
        "--ingest-workers", workers, "--batch-size", "1024",
        "--json", "--quiet", "--native", "off",
    ]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["overall"]["count"] == 7500
    assert doc["ingest_workers"] == min(int(workers), 3)
    assert doc["segments"]["files"] - before.files == 3
    assert doc["segments"]["records"] - before.records == 7500
    assert doc["segments"]["bytes_mapped"] > before.bytes_mapped
    assert "kta_segment_files_opened_total" in doc["telemetry"]


def test_make_segments_cli_roundtrip_and_flag_hint(tmp_path, capsys):
    """tools/make_segments: works with the --synthetic kv spec, and a user
    who tries per-key flags gets pointed at the spec form (r3 weak #6)."""
    from kafka_topic_analyzer_tpu.tools.make_segments import main as ms_main

    out = str(tmp_path / "segs")
    rc = ms_main(["--out", out, "--topic", "demo", "--native", "off",
                  "--synthetic", "partitions=2,messages=300,keys=40"])
    assert rc == 0
    import os
    assert sorted(os.listdir(out)) == ["demo-0.ktaseg", "demo-1.ktaseg"]

    with pytest.raises(SystemExit) as e:
        ms_main(["--out", out, "--topic", "demo",
                 "--partitions", "4", "--messages", "5000"])
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "--synthetic" in err and "partitions=" in err
    # Bad kv values still come back as one clean named-key line, rc 1.
    rc = ms_main(["--out", out, "--topic", "demo", "--native", "off",
                  "--synthetic", "nope=1"])
    assert rc == 1
    assert "unknown --synthetic key 'nope'" in capsys.readouterr().err
