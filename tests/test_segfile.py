"""Segment-dump roundtrip: write from a synthetic topic, re-scan, same report."""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.segfile import (
    SegmentFile,
    SegmentFileSource,
    write_segment_from_batches,
)
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

SPEC = SyntheticSpec(
    num_partitions=3,
    messages_per_partition=2_500,
    keys_per_partition=100,
    tombstone_permille=120,
    seed=5,
)


@pytest.fixture()
def seg_dir(tmp_path):
    src = SyntheticSource(SPEC)
    for p in src.partitions():
        write_segment_from_batches(
            str(tmp_path), "t", p, list(src.batches(1000, partitions=[p]))
        )
    return str(tmp_path)


def test_roundtrip_header_and_watermarks(seg_dir):
    src = SegmentFileSource(seg_dir, "t")
    assert src.partitions() == [0, 1, 2]
    start, end = src.watermarks()
    assert start == {0: 0, 1: 0, 2: 0}
    assert end == {0: 2500, 1: 2500, 2: 2500}
    seg = SegmentFile(f"{seg_dir}/t-0.ktaseg")
    assert seg.count == 2500 and seg.partition == 0


def test_segfile_scan_matches_synthetic_scan(seg_dir):
    cfg = AnalyzerConfig(num_partitions=3, batch_size=777, count_alive_keys=True,
                         alive_bitmap_bits=20)
    m_synth = run_scan(
        "t", SyntheticSource(SPEC), CpuExactBackend(cfg, init_now_s=10**10), 777
    ).metrics
    m_seg = run_scan(
        "t", SegmentFileSource(seg_dir, "t"), CpuExactBackend(cfg, init_now_s=10**10), 777
    ).metrics
    assert np.array_equal(m_synth.per_partition, m_seg.per_partition)
    assert m_synth.alive_keys == m_seg.alive_keys
    assert m_synth.earliest_ts_s == m_seg.earliest_ts_s
    assert m_synth.latest_ts_s == m_seg.latest_ts_s
    assert m_synth.smallest_message == m_seg.smallest_message
    assert m_synth.largest_message == m_seg.largest_message


def test_topic_name_prefix_not_confused(seg_dir):
    # A topic whose name extends the requested one must not be swallowed
    # by filename matching.
    src0 = SyntheticSource(SPEC)
    write_segment_from_batches(
        seg_dir, "t-extra", 0, list(src0.batches(1000, partitions=[0]))
    )
    src = SegmentFileSource(seg_dir, "t")
    assert src.partitions() == [0, 1, 2]
    _, end = src.watermarks()
    assert end[0] == 2500  # not the t-extra file's data


def test_dump_writer_roundtrip_with_gappy_offsets(tmp_path):
    """Dump a gappy (compacted) stream in rolled chunks, re-read it, and
    get identical metrics plus offset-exact watermarks."""
    from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter, TeeSource
    from kafka_topic_analyzer_tpu.io.kafka_wire import records_to_batch

    rows = []
    for off in range(0, 600, 3):  # offsets with gaps
        rows.append((0, 1_600_000_000_000 + off, f"k{off % 13}".encode(),
                     None if off % 7 == 0 else bytes(10 + off % 40)))
    batch = records_to_batch(rows)
    batch.offsets = np.arange(0, 600, 3, dtype=np.int64)

    # Append in 50-record batches; chunks roll once >= 64 records buffered
    # (rolling is batch-granular).
    writer = SegmentDumpWriter(str(tmp_path), "gap", records_per_chunk=64)
    for lo in range(0, 200, 50):
        writer.append(batch.take(np.arange(lo, lo + 50)))
    writer.close()

    src = SegmentFileSource(str(tmp_path), "gap")
    start, end = src.watermarks()
    assert start == {0: 0}
    assert end == {0: 598}  # last retained offset 597 + 1
    from kafka_topic_analyzer_tpu.records import RecordBatch

    full = RecordBatch.concat(list(src.batches(50)))
    assert len(full) == 200
    assert np.array_equal(full.offsets, batch.offsets)
    assert np.array_equal(full.key_len, batch.key_len)
    assert np.array_equal(full.value_null, batch.value_null)
    # Chunked files actually rolled.
    import os

    chunks = [f for f in os.listdir(tmp_path) if f.startswith("gap-0.c")]
    assert len(chunks) == 2  # rolled at 100 records (2 x 50-record appends)

    # Offset-exact resume mid-chunk.
    resumed = RecordBatch.concat(list(src.batches(50, start_at={0: 301})))
    assert int(resumed.offsets[0]) == 303  # first retained offset >= 301


def test_dump_preserves_nonzero_start_of_gapless_source(tmp_path):
    """Re-dumping an offset-less source that starts above 0 (retention) must
    keep the true start offset, not rebase to 0."""
    from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter, TeeSource

    src_dir = tmp_path / "src"
    dst_dir = tmp_path / "dst"
    src_dir.mkdir()
    src = SyntheticSource(SPEC)
    write_segment_from_batches(
        str(src_dir), "t", 0, list(src.batches(1000, partitions=[0])),
        start_offset=1000,
    )
    reader = SegmentFileSource(str(src_dir), "t")
    assert reader.watermarks()[0] == {0: 1000}
    tee = TeeSource(reader, SegmentDumpWriter(str(dst_dir), "t"))
    for _ in tee.batches(700):
        pass
    tee.close()
    redump = SegmentFileSource(str(dst_dir), "t")
    start, end = redump.watermarks()
    assert start == {0: 1000}
    assert end == {0: 1000 + SPEC.messages_per_partition}


def test_corrupt_magic_rejected(seg_dir, tmp_path):
    bad = tmp_path / "t-9.ktaseg"
    data = bytearray(open(f"{seg_dir}/t-0.ktaseg", "rb").read())
    data[:8] = b"NOTASEG!"
    bad.write_bytes(bytes(data))
    with pytest.raises(ValueError, match="bad magic"):
        SegmentFile(str(bad))


def test_make_segments_cli_roundtrip_and_flag_hint(tmp_path, capsys):
    """tools/make_segments: works with the --synthetic kv spec, and a user
    who tries per-key flags gets pointed at the spec form (r3 weak #6)."""
    from kafka_topic_analyzer_tpu.tools.make_segments import main as ms_main

    out = str(tmp_path / "segs")
    rc = ms_main(["--out", out, "--topic", "demo", "--native", "off",
                  "--synthetic", "partitions=2,messages=300,keys=40"])
    assert rc == 0
    import os
    assert sorted(os.listdir(out)) == ["demo-0.ktaseg", "demo-1.ktaseg"]

    with pytest.raises(SystemExit) as e:
        ms_main(["--out", out, "--topic", "demo",
                 "--partitions", "4", "--messages", "5000"])
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "--synthetic" in err and "partitions=" in err
    # Bad kv values still come back as one clean named-key line, rc 1.
    rc = ms_main(["--out", out, "--topic", "demo", "--native", "off",
                  "--synthetic", "nope=1"])
    assert rc == 1
    assert "unknown --synthetic key 'nope'" in capsys.readouterr().err
