"""Scan honesty under a mutating log (ISSUE 18; DESIGN.md §24).

The log is not frozen while we scan it: retention deletes from the tail
we have not reached, unclean elections replace batches we already folded.
The contract under test:

- ACCOUNTING: every record the log takes back mid-scan is booked as a
  lost span — [old cursor, new log start) for a retention race,
  [divergence, end watermark) for a truncation — and the scan's metrics
  are BYTE-IDENTICAL to a clean scan of exactly the surviving records.
  Nothing is lost silently, nothing is double-counted, across ingest
  workers × superbatch K.
- FENCING: the client tracks partition_leader_epoch from batch headers
  and sends it on flexible fetches; FENCED/UNKNOWN_LEADER_EPOCH answers
  run the OffsetForLeaderEpoch divergence probe, and truncation below
  the cursor marks the fold non-authoritative instead of rewinding into
  the replacement log.  A clean election (no truncation) costs fence
  round-trips but never records or loss.
- POLICY: --on-data-loss decides the exit alone — fail aborts with exit
  5, report exits 0 WITH the DATA-LOSS block, ignore exits 0 without
  it.  Loss never changes the exit code outside the fail policy.
- DURABILITY: checkpoints carry the lost spans and per-partition
  {leader_epoch, log_start_offset}; a resume below the live log start
  is a named loss, and a successor instance INHERITS its predecessor's
  booked loss without re-counting it (fleet failover).
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.checkpoint import (
    load_lost_spans,
    load_partition_meta,
)
from kafka_topic_analyzer_tpu.cli import EXIT_DATA_LOSS, main
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    DispatchConfig,
    FollowConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.fleet.scheduler import FleetScheduler, TopicSeed
from kafka_topic_analyzer_tpu.fleet.service import FleetService
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.serve.follow import FollowService

from fake_broker import FakeBroker

pytestmark = pytest.mark.logmut

TOPIC = "logmut.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}

#: Tight service pacing so the follow-churn test stays inside tier-1.
FAST_FOLLOW = dict(
    poll_interval_s=0.02,
    idle_backoff_max_s=0.05,
    window_secs=5.0,
    window_count=4,
)


def _rows(partition: int, n: int, lo: int = 0):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 31}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(lo, lo + n)
    ]


def _cfg(parts: int = 1, **kw) -> AnalyzerConfig:
    base = dict(
        num_partitions=parts, batch_size=128,
        count_alive_keys=True, alive_bitmap_bits=16,
    )
    base.update(kw)
    return AnalyzerConfig(**base)


def _metrics_doc(result) -> dict:
    return result.metrics.to_dict(result.start_offsets, result.end_offsets)


def _loss_counters(reason: str):
    return (
        obs_metrics.LOG_LOST_RECORDS.labels(reason=reason).value,
        obs_metrics.LOG_LOST_RANGES.labels(reason=reason).value,
    )


class _FetchHook:
    """response_delay hook that fires ``action`` right after the broker
    ENCODES its ``fire_at``-th fetch response (the hook runs between
    _dispatch and the socket send), so the mutation lands before the
    client can have acted on that response — the cursor positions at the
    next fetch are deterministic for a sequential stream."""

    def __init__(self, fire_at: int, action):
        self.fire_at = fire_at
        self.action = action
        self.fetches = 0
        self.fired = False

    def __call__(self, api_key: int, node_id: int) -> float:
        if api_key == kc.API_FETCH:
            self.fetches += 1
            if self.fetches == self.fire_at and not self.fired:
                self.fired = True
                self.action()
        return 0.0


class _Interrupt(Exception):
    pass


def _wait_for(predicate, timeout_s=20.0, interval_s=0.01, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


def _published_count(svc) -> int:
    doc = svc.state.snapshot()
    return doc["overall"]["count"] if doc else -1


# ---------------------------------------------------------------------------
# retention race: the log's tail expires while the scan is mid-flight


def test_mid_scan_retention_books_exact_range():
    """Retention fires while the cursor is at 150: the re-anchor books
    EXACTLY [150, 200) and the metrics equal a clean scan of the
    survivors — chunk math: 50-record fetches, expiry after response #3
    (covering [100, 150)) pins the next fetch at offset 150."""
    rows = _rows(0, 400)
    before = _loss_counters("retention")
    with FakeBroker(TOPIC, {0: list(rows)}, max_records_per_fetch=50) as broker:
        hook = _FetchHook(3, lambda: broker.expire_to(0, 200))
        broker.response_delay = hook
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        result = run_scan(TOPIC, src, CpuExactBackend(_cfg(), init_now_s=10**10), 128)
        src.close()
    assert hook.fired
    assert not result.degraded_partitions
    assert set(result.lost_partitions) == {0}
    d = result.lost_partitions[0]
    assert d["records"] == 50
    assert d["ranges"] == 1
    assert d["authoritative"] is True
    assert d["reasons"] == {"retention": 1}
    (span,) = d["spans"]
    assert (span["start"], span["end"], span["reason"]) == (150, 200, "retention")
    after = _loss_counters("retention")
    assert after[0] - before[0] == 50
    assert after[1] - before[1] == 1

    survivors = [r for r in rows if not (150 <= r[0] < 200)]
    with FakeBroker(TOPIC, {0: survivors}, max_records_per_fetch=50) as ref_broker:
        ref_src = KafkaWireSource(
            f"127.0.0.1:{ref_broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        referee = run_scan(
            TOPIC, ref_src, CpuExactBackend(_cfg(), init_now_s=10**10), 128
        )
        ref_src.close()
    assert not referee.lost_partitions
    assert _metrics_doc(result) == _metrics_doc(referee)
    assert result.metrics.to_dict({0: 0}, {0: 400})["overall"]["count"] == 350


@pytest.mark.parametrize("workers,k,d", [(2, 1, 1), (3, 2, 2)])
def test_retention_race_under_workers_and_superbatch(workers, k, d):
    """The accounting contract holds when partitions are sharded across
    ingest workers and batches fold through a superbatch window: the
    cursor positions at expiry are nondeterministic, so the referee is
    RECONSTRUCTED from the booked spans — survivors = log minus spans —
    and byte-identity plus per-partition conservation (folded + lost ==
    produced) proves every expired record was either folded first or
    booked, never silently skipped."""
    records = {p: _rows(p, 400) for p in range(3)}
    cfg = _cfg(parts=3)
    with FakeBroker(
        TOPIC, {p: list(r) for p, r in records.items()}, max_records_per_fetch=50
    ) as broker:
        hook = _FetchHook(
            2, lambda: [broker.expire_to(p, 300) for p in range(3)]
        )
        broker.response_delay = hook
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        backend = TpuBackend(
            cfg, init_now_s=10**10, dispatch=DispatchConfig(superbatch=k, depth=d)
        )
        result = run_scan(TOPIC, src, backend, 128, ingest_workers=workers)
        src.close()
    assert hook.fired
    assert not result.degraded_partitions
    # Expiry landed before any cursor could reach 300, so every partition
    # lost a range ending exactly at the new log start.
    assert set(result.lost_partitions) == {0, 1, 2}
    survivors = {}
    for p in range(3):
        d_p = result.lost_partitions[p]
        assert d_p["authoritative"] is True
        (span,) = d_p["spans"]
        assert span["reason"] == "retention"
        assert span["end"] == 300
        assert 0 <= span["start"] < 300
        assert span["records"] == span["end"] - span["start"]
        gone = set(range(span["start"], span["end"]))
        survivors[p] = [r for r in records[p] if r[0] not in gone]
        assert len(survivors[p]) + len(gone) == 400  # conservation
    with FakeBroker(TOPIC, survivors, max_records_per_fetch=50) as ref_broker:
        ref_src = KafkaWireSource(
            f"127.0.0.1:{ref_broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        referee = run_scan(
            TOPIC,
            ref_src,
            TpuBackend(
                cfg, init_now_s=10**10,
                dispatch=DispatchConfig(superbatch=k, depth=d),
            ),
            128,
            ingest_workers=workers,
        )
        ref_src.close()
    S = {p: 0 for p in range(3)}
    E = {p: 400 for p in range(3)}
    assert result.metrics.to_dict(S, E) == referee.metrics.to_dict(S, E)


# ---------------------------------------------------------------------------
# leader-epoch fencing: elections mid-scan


def test_unclean_election_truncation_is_non_authoritative():
    """An unclean election truncates to 100 while the cursor is at 150:
    the next fetch (sending the tracked epoch 0) is FENCED, the
    OffsetForLeaderEpoch probe finds epoch 0's log ends at 100 < cursor,
    and the WHOLE destroyed range [100, 400) is booked as truncation —
    the fold keeps the 150 records it already made (marked
    non-authoritative), and the cursor never rewinds into the
    replacement log (no double count)."""
    rows = _rows(0, 400)
    before = _loss_counters("truncation")
    fences0 = obs_metrics.LOG_EPOCH_FENCES.value
    checks0 = obs_metrics.LOG_DIVERGENCE_CHECKS.value
    with FakeBroker(
        TOPIC, {0: list(rows)}, max_records_per_fetch=50, modern=True
    ) as broker:
        hook = _FetchHook(3, lambda: broker.unclean_elect(0, truncate_to=100))
        broker.response_delay = hook
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        result = run_scan(TOPIC, src, CpuExactBackend(_cfg(), init_now_s=10**10), 128)
        src.close()
    assert hook.fired
    assert not result.degraded_partitions
    assert obs_metrics.LOG_EPOCH_FENCES.value - fences0 >= 1
    assert obs_metrics.LOG_DIVERGENCE_CHECKS.value - checks0 >= 1
    d = result.lost_partitions[0]
    assert d["authoritative"] is False
    (span,) = d["spans"]
    assert (span["start"], span["end"], span["reason"]) == (100, 400, "truncation")
    assert span["records"] == 300
    after = _loss_counters("truncation")
    assert after[0] - before[0] == 300
    assert after[1] - before[1] == 1

    # The fold covers exactly the 150 records read before the election.
    with FakeBroker(TOPIC, {0: rows[:150]}, max_records_per_fetch=50) as ref_broker:
        ref_src = KafkaWireSource(
            f"127.0.0.1:{ref_broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        referee = run_scan(
            TOPIC, ref_src, CpuExactBackend(_cfg(), init_now_s=10**10), 128
        )
        ref_src.close()
    assert result.metrics.to_dict({0: 0}, {0: 400}) == referee.metrics.to_dict(
        {0: 0}, {0: 400}
    )


def test_clean_election_costs_fences_but_never_records():
    """A leadership change WITHOUT truncation: the fenced fetch runs the
    divergence probe, finds epoch 0's log intact at/above the cursor,
    and the scan finishes byte-identical to an undisturbed run — fences
    and divergence checks are booked, loss is not."""
    rows = _rows(0, 400)
    fences0 = obs_metrics.LOG_EPOCH_FENCES.value
    with FakeBroker(
        TOPIC, {0: list(rows)}, max_records_per_fetch=50, modern=True
    ) as broker:
        hook = _FetchHook(3, lambda: broker.unclean_elect(0))
        broker.response_delay = hook
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        result = run_scan(TOPIC, src, CpuExactBackend(_cfg(), init_now_s=10**10), 128)
        src.close()
    assert hook.fired
    assert not result.degraded_partitions
    assert not result.lost_partitions
    assert obs_metrics.LOG_EPOCH_FENCES.value - fences0 >= 1

    with FakeBroker(
        TOPIC, {0: list(rows)}, max_records_per_fetch=50, modern=True
    ) as ref_broker:
        ref_src = KafkaWireSource(
            f"127.0.0.1:{ref_broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        referee = run_scan(
            TOPIC, ref_src, CpuExactBackend(_cfg(), init_now_s=10**10), 128
        )
        ref_src.close()
    assert _metrics_doc(result) == _metrics_doc(referee)
    assert _metrics_doc(result)["overall"]["count"] == 400


# ---------------------------------------------------------------------------
# --on-data-loss policy: the exit-code contract


def _cli_args(broker, *extra):
    return [
        "-t", TOPIC,
        "-b", f"127.0.0.1:{broker.port}",
        "--librdkafka", "retry.backoff.ms=5,reconnect.backoff.max.ms=40",
        "--backend", "cpu", "-c", "--alive-bitmap-bits", "16",
        "--quiet", "--native", "off",
        *extra,
    ]


@pytest.mark.parametrize(
    "policy,rc,has_block",
    [("fail", EXIT_DATA_LOSS, None), ("report", 0, True), ("ignore", 0, False)],
)
def test_cli_on_data_loss_policy_exits(policy, rc, has_block, capsys):
    """fail aborts with exit 5; report finishes with exit 0 AND the
    DATA-LOSS block; ignore finishes with exit 0 and no block.  The
    exit code outside the fail policy never reflects loss."""
    with FakeBroker(TOPIC, {0: _rows(0, 400)}, max_records_per_fetch=50) as broker:
        broker.response_delay = _FetchHook(3, lambda: broker.expire_to(0, 200))
        assert main(_cli_args(broker, "--on-data-loss", policy)) == rc
    out = capsys.readouterr().out
    if has_block is True:
        assert "DATA-LOSS" in out
    elif has_block is False:
        assert "DATA-LOSS" not in out


def test_cli_json_carries_data_loss_map(capsys):
    """--json under the default report policy: exit 0, parseable doc,
    and a data_loss map with the exact booked span."""
    with FakeBroker(TOPIC, {0: _rows(0, 400)}, max_records_per_fetch=50) as broker:
        broker.response_delay = _FetchHook(3, lambda: broker.expire_to(0, 200))
        assert main(_cli_args(broker, "--json", "--on-data-loss", "report")) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["overall"]["count"] == 350
    loss = doc["data_loss"]["0"]
    assert loss["records"] == 50
    assert loss["authoritative"] is True
    (span,) = loss["spans"]
    assert (span["start"], span["end"], span["reason"]) == (150, 200, "retention")


# ---------------------------------------------------------------------------
# durability: checkpoints carry the loss facts across lives


def test_resume_below_log_start_books_named_loss(tmp_path):
    """Retention outruns a checkpoint: session 1 stops at offset 256,
    the log start advances to 300, and the resumed session books the gap
    [256, 300) as resume-below-log-start BEFORE its first fetch — then
    finishes byte-identical to a clean scan of what survived both lives.
    The final snapshot re-exports the span and the partition meta for
    the next life."""
    rows = _rows(0, 400)
    cfg = _cfg()
    before = _loss_counters("resume-below-log-start")
    with FakeBroker(TOPIC, {0: list(rows)}, max_records_per_fetch=50) as broker:
        bootstrap = f"127.0.0.1:{broker.port}"
        src1 = KafkaWireSource(bootstrap, TOPIC, overrides=dict(FAST_RETRY))

        class Half:
            def __getattr__(self, name):
                return getattr(src1, name)

            def batches(self, batch_size, partitions=None, start_at=None):
                it = src1.batches(batch_size, partitions, start_at)
                for i, b in enumerate(it):
                    if i >= 2:
                        raise _Interrupt()
                    yield b

        with pytest.raises(_Interrupt):
            run_scan(
                TOPIC, Half(), TpuBackend(cfg, init_now_s=10**10), 128,
                snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
            )
        src1.close()

        broker.expire_to(0, 300)
        src2 = KafkaWireSource(bootstrap, TOPIC, overrides=dict(FAST_RETRY))
        result = run_scan(
            TOPIC, src2, TpuBackend(cfg, init_now_s=10**10), 128,
            snapshot_dir=str(tmp_path), resume=True,
        )
        src2.close()
    d = result.lost_partitions[0]
    (span,) = d["spans"]
    assert (span["start"], span["end"], span["reason"]) == (
        256, 300, "resume-below-log-start",
    )
    after = _loss_counters("resume-below-log-start")
    assert after[0] - before[0] == 44
    assert after[1] - before[1] == 1

    # The loss-carrying final snapshot: spans + partition meta round-trip.
    saved = load_lost_spans(str(tmp_path))
    assert any(
        s["start"] == 256 and s["end"] == 300
        and s["reason"] == "resume-below-log-start"
        for s in saved
    )
    meta = load_partition_meta(str(tmp_path))
    assert meta and meta[0]["log_start_offset"] >= 300

    survivors = rows[:256] + rows[300:]
    with FakeBroker(TOPIC, {0: survivors}, max_records_per_fetch=50) as ref_broker:
        ref_src = KafkaWireSource(
            f"127.0.0.1:{ref_broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        referee = run_scan(
            TOPIC, ref_src, TpuBackend(cfg, init_now_s=10**10), 128
        )
        ref_src.close()
    assert result.metrics.to_dict({0: 0}, {0: 400}) == referee.metrics.to_dict(
        {0: 0}, {0: 400}
    )


def test_follow_retention_churn_across_polls():
    """Two retention cycles land between follow polls, each expiring past
    the follower's cursor: every cycle books its exact never-served gap
    [cursor, new start), the cursor re-anchors forward, and the final
    fold counts exactly the records that were ever fetchable."""
    follow = FollowConfig(**FAST_FOLLOW)
    with FakeBroker(TOPIC, {0: _rows(0, 150)}, max_records_per_fetch=50) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        svc = FollowService(
            TOPIC, src, CpuExactBackend(_cfg(batch_size=64), init_now_s=10**10),
            64, follow,
        )
        errors = []

        def driver():
            try:
                _wait_for(
                    lambda: _published_count(svc) >= 150, what="phase-1 fold"
                )
                # Cycle 1: retention jumps the log to [200, ..) — the
                # follower (at 150) never saw [150, 200).
                broker.expire_to(0, 200)
                broker.produce(0, _rows(0, 100, lo=200))
                _wait_for(
                    lambda: _published_count(svc) >= 250, what="cycle-1 fold"
                )
                # Cycle 2: again, from [300, ..) to [350, ..).
                broker.expire_to(0, 350)
                broker.produce(0, _rows(0, 50, lo=350))
                _wait_for(
                    lambda: _published_count(svc) >= 300, what="cycle-2 fold"
                )
            except BaseException as e:  # surfaced after join
                errors.append(e)
            finally:
                svc.request_stop("test")

        t = threading.Thread(target=driver)
        t.start()
        result = svc.run()
        t.join()
        src.close()
        if errors:
            raise errors[0]
    d = result.lost_partitions[0]
    assert d["records"] == 100
    assert d["ranges"] == 2
    assert d["reasons"] == {"retention": 2}
    got = sorted((s["start"], s["end"]) for s in d["spans"])
    assert got == [(150, 200), (300, 350)]
    assert result.metrics.to_dict({0: 0}, {0: 400})["overall"]["count"] == 300


def test_fleet_failover_inherits_loss_from_checkpoint(tmp_path):
    """Instance A books a retention loss and checkpoints it; instance B
    resumes the fleet from A's snapshots and must INHERIT the booked
    loss — same per-topic lost_records in the rollup, spans marked
    seeded — without re-incrementing the global loss counters, and
    without tripping any_data_loss (loss under the report policy never
    changes the fleet exit)."""
    topics = ["logmut.a", "logmut.b"]
    recs = {t: {0: _rows(i, 400)} for i, t in enumerate(topics)}

    def mk_fleet(broker, resume):
        def source_factory(topic):
            return KafkaWireSource(
                f"127.0.0.1:{broker.port}", topic, overrides=dict(FAST_RETRY)
            )

        def backend_factory(topic, parts, grant):
            # Snapshot-capable backend: the inheritance under test rides
            # the per-topic checkpoints.
            return TpuBackend(_cfg(batch_size=64), init_now_s=10**10)

        seeds = [TopicSeed(name=t, partitions=1) for t in topics]
        return FleetService(
            seeds, source_factory, backend_factory, 64,
            FleetScheduler(2, 2, 2),
            snapshot_dir=str(tmp_path), resume=resume,
        )

    with FakeBroker(
        topics[0], recs[topics[0]],
        extra_topics={topics[1]: recs[topics[1]]},
        max_records_per_fetch=50,
    ) as broker:
        hook = _FetchHook(
            2, lambda: [broker.expire_to(0, 300, topic=t) for t in topics]
        )
        broker.response_delay = hook
        fr_a = mk_fleet(broker, resume=False).run_batch()
        assert hook.fired
        lost_a = {t: fr_a.statuses[t].lost_records for t in topics}
        assert sum(lost_a.values()) > 0
        assert not fr_a.any_data_loss
        assert all(fr_a.statuses[t].status == "ok" for t in topics)

        before = _loss_counters("retention")
        svc_b = mk_fleet(broker, resume=True)
        fr_b = svc_b.run_batch()
    # Inherited, not re-counted.
    assert _loss_counters("retention") == before
    assert not fr_b.any_data_loss
    for t in topics:
        assert fr_b.statuses[t].lost_records == lost_a[t]
        if lost_a[t]:
            spans = svc_b.scans[t].result.lost_partitions[0]["spans"]
            assert spans and all(s.get("seeded") for s in spans)
