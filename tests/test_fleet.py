"""Fleet mode (ISSUE 13): cluster-wide discovery, admission, isolation.

The contract under test, per DESIGN.md §20:

- DISCOVERY: one all-topics Metadata request lists every topic with its
  internal flag; glob include/exclude + internal exclusion filter it;
  mid-test topic creation is visible to a re-discovery;
- ADMISSION ALGEBRA: at every point in any admit/release/rebalance
  sequence, granted workers/dispatch never exceed the budgets, every
  active grant keeps >= 1 of each, and workers never exceed a topic's
  partition count;
- BYTE-IDENTITY: a fleet scan's per-topic metrics equal solo scans of
  the same topics (swept over workers x superbatch), and agree with the
  MultiTopicSource fan-in's slice_rows projection — the two independent
  oracles;
- ISOLATION: one topic's deterministic corruption (fail policy) marks
  THAT topic failed in the status table; every other topic's results are
  byte-identical to its solo scan;
- DURABILITY: fleet follow SIGTERM lands per-topic checkpoints in
  per-topic subdirectories, and a restarted fleet resumes each topic
  with no loss and no double-count;
- SURFACES: /report.json serves the cluster rollup, ?topic= each solo
  --json-schema document; the CLI's --fleet --json and the lifted
  multi-topic --follow path work end to end.
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    DispatchConfig,
    FollowConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.fleet.discovery import (
    DiscoveredTopic,
    discover_topics,
    filter_topics,
    parse_globs,
)
from kafka_topic_analyzer_tpu.fleet.scheduler import (
    FleetScheduler,
    TopicSeed,
)
from kafka_topic_analyzer_tpu.fleet.service import FleetService
from kafka_topic_analyzer_tpu.io.kafka_wire import (
    KafkaWireSource,
    discover_cluster_topics,
)
from kafka_topic_analyzer_tpu.serve import state as serve_state

from fake_broker import CorruptionInjector, FakeBroker

pytestmark = pytest.mark.fleet

TOPICS = ["fleet.a", "fleet.b", "fleet.c"]
N_PARTS = 4
PHASE1_N = 60
PHASE2_N = 30
FULL_N = PHASE1_N + PHASE2_N

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}

FAST_FOLLOW = dict(
    poll_interval_s=0.02,
    idle_backoff_max_s=0.05,
)


def _mk_records(salt: int, partition: int, lo: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{salt}-{partition}-{i % 17}".encode() if i % 5 else None,
            bytes(15 + ((i + salt) % 11)) if i % 7 else None,
        )
        for i in range(lo, lo + n)
    ]


def _topic_records(salt: int, n: int, lo: int = 0):
    return {p: _mk_records(salt, p, lo, n) for p in range(N_PARTS)}


FULL = {t: _topic_records(i, FULL_N) for i, t in enumerate(TOPICS)}
PHASE1 = {t: _topic_records(i, PHASE1_N) for i, t in enumerate(TOPICS)}
PHASE2 = {
    t: _topic_records(i, PHASE2_N, lo=PHASE1_N) for i, t in enumerate(TOPICS)
}
INTERNAL = {"__consumer_offsets": {0: _mk_records(99, 0, 0, 5)}}


def _mk_broker(records_by_topic, **kw):
    names = list(records_by_topic)
    return FakeBroker(
        names[0],
        records_by_topic[names[0]],
        extra_topics={t: records_by_topic[t] for t in names[1:]},
        internal_topics=dict(INTERNAL),
        max_records_per_fetch=48,
        **kw,
    )


def _cfg(parts=N_PARTS, **kw) -> AnalyzerConfig:
    base = dict(
        num_partitions=parts,
        batch_size=64,
        count_alive_keys=True,
        alive_bitmap_bits=16,
        enable_hll=True,
        hll_p=8,
        enable_quantiles=True,
        quantiles_per_partition=True,
    )
    base.update(kw)
    return AnalyzerConfig(**base)


def _source(broker, topic, **overrides):
    return KafkaWireSource(
        f"127.0.0.1:{broker.port}", topic,
        overrides=dict(FAST_RETRY, **overrides),
    )


def _metrics_doc(result) -> dict:
    return result.metrics.to_dict(result.start_offsets, result.end_offsets)


def _fleet_service(
    broker,
    topics=TOPICS,
    worker_budget=3,
    dispatch_budget=3,
    max_concurrent=3,
    superbatch=1,
    follow=None,
    source_overrides=None,
    **kw,
):
    scheduler = FleetScheduler(worker_budget, dispatch_budget, max_concurrent)

    def source_factory(topic):
        return _source(broker, topic, **(source_overrides or {}))

    def backend_factory(topic, parts, grant):
        return TpuBackend(
            _cfg(parts),
            dispatch=DispatchConfig(
                superbatch=superbatch, depth=grant.dispatch_depth
            ),
            init_now_s=10**10,
        )

    seeds = [TopicSeed(name=t, partitions=N_PARTS) for t in topics]
    return FleetService(
        seeds, source_factory, backend_factory, 64, scheduler,
        follow=follow, **kw,
    )


def _wait_for(predicate, timeout_s=30.0, interval_s=0.01, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# discovery


def test_discover_cluster_topics_lists_all_with_internal_flags():
    with _mk_broker(FULL) as broker:
        mds = discover_cluster_topics(f"127.0.0.1:{broker.port}")
    by_name = {t.name: t for t in mds}
    assert set(by_name) == set(TOPICS) | {"__consumer_offsets"}
    assert by_name["__consumer_offsets"].is_internal == 1
    for t in TOPICS:
        assert by_name[t].is_internal == 0
        assert len(by_name[t].partitions) == N_PARTS


def test_discovery_filters_globs_and_internal():
    with _mk_broker(FULL) as broker:
        bootstrap = f"127.0.0.1:{broker.port}"
        # Default: every user topic, internal excluded.
        ds = discover_topics(bootstrap)
        assert [d.name for d in ds] == sorted(TOPICS)
        assert all(d.partitions == N_PARTS for d in ds)
        # Include glob narrows.
        ds = discover_topics(bootstrap, include=["*.a"])
        assert [d.name for d in ds] == ["fleet.a"]
        # Exclude applies after include.
        ds = discover_topics(bootstrap, include=["fleet.*"], exclude=["*.b"])
        assert [d.name for d in ds] == ["fleet.a", "fleet.c"]
        # Internal opt-in.
        ds = discover_topics(bootstrap, include_internal=True)
        assert "__consumer_offsets" in [d.name for d in ds]


def test_discovery_sees_mid_test_topic_creation():
    with _mk_broker(FULL) as broker:
        bootstrap = f"127.0.0.1:{broker.port}"
        assert [d.name for d in discover_topics(bootstrap)] == sorted(TOPICS)
        broker.create_topic("fleet.new", {0: _mk_records(7, 0, 0, 10)})
        assert [d.name for d in discover_topics(bootstrap)] == sorted(
            TOPICS + ["fleet.new"]
        )
        # Internal mid-test creation stays excluded by default.
        broker.create_topic(
            "__txn_state", {0: _mk_records(8, 0, 0, 3)}, internal=True
        )
        assert "__txn_state" not in [
            d.name for d in discover_topics(bootstrap)
        ]


def test_discovery_empty_cluster():
    # A cluster whose only topic is internal: the fleet has nothing to do.
    with FakeBroker(
        "__consumer_offsets", {0: []}, max_records_per_fetch=48
    ) as broker:
        assert discover_topics(f"127.0.0.1:{broker.port}") == []


def test_filter_topics_unit():
    topics = [
        DiscoveredTopic("orders", 4),
        DiscoveredTopic("orders.dlq", 1),
        DiscoveredTopic("users", 2),
        DiscoveredTopic("__consumer_offsets", 50, internal=True),
        DiscoveredTopic("__unflagged_system", 1),  # name-prefix rule
    ]
    # `__unflagged_system` carries internal=False from this fake metadata,
    # but discover_topics flags the name prefix; filter_topics only sees
    # the flag — mark it the way discovery would.
    topics[-1] = DiscoveredTopic("__unflagged_system", 1, internal=True)
    assert [t.name for t in filter_topics(topics)] == [
        "orders", "orders.dlq", "users",
    ]
    assert [t.name for t in filter_topics(topics, include=["orders*"])] == [
        "orders", "orders.dlq",
    ]
    assert [
        t.name
        for t in filter_topics(
            topics, include=["orders*"], exclude=["*.dlq"]
        )
    ] == ["orders"]
    assert "__consumer_offsets" in [
        t.name for t in filter_topics(topics, include_internal=True)
    ]
    assert filter_topics([]) == []
    assert parse_globs(" a , b ,") == ["a", "b"]
    assert parse_globs(None) == []


# ---------------------------------------------------------------------------
# the admission algebra


def _assert_invariants(sched: FleetScheduler, partitions):
    assert sched.workers_granted <= sched.worker_budget
    assert sched.dispatch_granted <= sched.dispatch_budget
    assert sched.active <= sched.max_concurrent
    for t, g in sched.grants().items():
        assert g.workers >= 1
        assert g.dispatch_depth >= 1
        assert g.workers <= max(1, partitions[t])


def test_scheduler_budget_conservation_property():
    """Sum of granted workers/dispatch <= the budgets at EVERY point of
    arbitrary admit/release/rebalance sequences (seeded, deterministic)."""
    rng = random.Random(1234)
    for trial in range(20):
        wb = rng.randint(1, 16)
        db = rng.randint(1, 8)
        mc = rng.randint(1, 6)
        sched = FleetScheduler(wb, db, mc)
        partitions = {
            f"t{i}": rng.randint(1, 12) for i in range(rng.randint(1, 10))
        }
        for _ in range(40):
            op = rng.random()
            if op < 0.45:
                ready = [
                    TopicSeed(t, partitions[t], lag=rng.randint(0, 1000))
                    for t in rng.sample(
                        sorted(partitions), rng.randint(1, len(partitions))
                    )
                ]
                sched.admit(ready)
            elif op < 0.7:
                grants = sched.grants()
                if grants:
                    sched.release(rng.choice(sorted(grants)))
            else:
                verdicts = {
                    t: rng.choice(
                        ["ingest-bound", "dispatch-bound", "balanced"]
                    )
                    for t in sched.grants()
                }
                sched.rebalance(verdicts)
            _assert_invariants(sched, partitions)


def test_scheduler_plan_waves_covers_all_within_bound():
    sched = FleetScheduler(8, 4, max_concurrent=2)
    seeds = [TopicSeed(f"t{i}", 2, lag=(i + 1) * 100) for i in range(7)]
    waves = sched.plan_waves(seeds)
    flat = [t for w in waves for t in w]
    assert sorted(flat) == sorted(s.name for s in seeds)  # each exactly once
    assert all(len(w) <= 2 for w in waves)
    assert sched.plan_waves([]) == []


def test_scheduler_rebalance_rule():
    sched = FleetScheduler(worker_budget=6, dispatch_budget=4, max_concurrent=2)
    sched.admit([TopicSeed("a", 8, lag=100), TopicSeed("b", 8, lag=90)])
    ga, gb = sched.grant_for("a"), sched.grant_for("b")
    assert ga.workers + gb.workers <= 6
    assert ga.dispatch_depth >= 1 and gb.dispatch_depth >= 1
    moves = sched.rebalance({"a": "dispatch-bound", "b": "ingest-bound"})
    assert moves > 0
    ga2, gb2 = sched.grant_for("a"), sched.grant_for("b")
    assert ga2.workers < ga.workers          # dispatch-bound shed a worker
    assert gb2.dispatch_depth == 1           # ingest-bound shed dispatch
    assert gb2.workers > gb.workers          # ...and drew from the pool
    _assert_invariants(sched, {"a": 8, "b": 8})
    # Balanced verdicts hold still.
    before = {t: (g.workers, g.dispatch_depth)
              for t, g in sched.grants().items()}
    assert sched.rebalance({"a": "balanced", "b": "balanced"}) == 0
    assert before == {
        t: (g.workers, g.dispatch_depth) for t, g in sched.grants().items()
    }


# ---------------------------------------------------------------------------
# fleet-vs-solo byte-identity


@pytest.fixture(scope="module")
def solo_referee():
    """Solo scans of each topic — the byte-exact referee docs."""
    docs = {}
    with _mk_broker(FULL) as broker:
        for topic in TOPICS:
            src = _source(broker, topic)
            result = run_scan(
                topic, src, TpuBackend(_cfg(), init_now_s=10**10), 64
            )
            src.close()
            docs[topic] = _metrics_doc(result)
    return docs


@pytest.mark.parametrize("workers,superbatch", [
    (1, 1), (4, 1), (1, 4), (4, 4),
])
def test_fleet_batch_byte_identity_matrix(solo_referee, workers, superbatch):
    with _mk_broker(FULL) as broker:
        svc = _fleet_service(
            broker,
            worker_budget=workers * len(TOPICS),
            dispatch_budget=2 * len(TOPICS),
            superbatch=superbatch,
        )
        fr = svc.run_batch()
    assert set(fr.results) == set(TOPICS)
    for topic in TOPICS:
        assert fr.statuses[topic].status == "ok"
        assert _metrics_doc(fr.results[topic]) == solo_referee[topic]
    if workers == 4:
        # The budget actually split: every topic's scan ran its granted
        # worker count (clamped at the partition count).
        assert all(
            fr.results[t].ingest_workers == min(4, N_PARTS) for t in TOPICS
        )
    # The rollup totals equal the sum of the referees.
    totals = fr.rollup["fleet"]["totals"]
    assert totals["records"] == sum(
        d["overall"]["count"] for d in solo_referee.values()
    )
    assert totals["bytes"] == sum(
        d["overall"]["size_bytes"] for d in solo_referee.values()
    )


def test_fleet_matches_fan_in_projection_oracle():
    """The second oracle (ISSUE 13): the MultiTopicSource fan-in scan's
    per-topic slice_rows projection must agree with the fleet's per-topic
    results — two entirely different multi-topic paths, one answer."""
    from kafka_topic_analyzer_tpu.io.multi import MultiTopicSource
    from kafka_topic_analyzer_tpu.results import slice_rows

    plain = dict(
        count_alive_keys=False, enable_hll=False, enable_quantiles=False,
        quantiles_per_partition=False,
    )
    with _mk_broker(FULL) as broker:
        # Fleet scan (plain config: slices can't carry merged sketches).
        scheduler = FleetScheduler(3, 3, 3)
        svc = FleetService(
            [TopicSeed(name=t, partitions=N_PARTS) for t in TOPICS],
            lambda t: _source(broker, t),
            lambda t, parts, grant: TpuBackend(
                _cfg(parts, **plain), init_now_s=10**10
            ),
            64,
            scheduler,
        )
        fr = svc.run_batch()
        # Fan-in oracle over the same topics.
        multi = MultiTopicSource(
            [(t, _source(broker, t)) for t in TOPICS]
        )
        union = run_scan(
            "fanin", multi,
            TpuBackend(
                _cfg(len(multi.partitions()), **plain), init_now_s=10**10
            ),
            64,
        ).metrics
        multi.close()
    for topic in TOPICS:
        rows = multi.rows_for(topic)
        ids = [multi.true_partition(r) for r in rows]
        sliced = slice_rows(union, rows, ids)
        solo = fr.results[topic].metrics
        assert np.array_equal(sliced.per_partition, solo.per_partition)
        assert sliced.overall_count == solo.overall_count
        assert sliced.overall_size == solo.overall_size
        assert sliced.earliest_ts_s == solo.earliest_ts_s
        assert sliced.latest_ts_s == solo.latest_ts_s
        assert sliced.smallest_message == solo.smallest_message
        assert sliced.largest_message == solo.largest_message


# ---------------------------------------------------------------------------
# isolation: one poisoned topic cannot take the fleet down


def test_one_topic_poisoned_isolation(solo_referee):
    # fleet.a (the broker's default topic) serves a deterministically
    # corrupt frame; the default --on-corruption=fail aborts THAT scan.
    corruption = CorruptionInjector().corrupt_length(partition=0, chunk=0)
    with _mk_broker(FULL, corruption=corruption) as broker:
        svc = _fleet_service(broker)
        fr = svc.run_batch()
    assert fr.statuses["fleet.a"].status == "failed"
    assert fr.statuses["fleet.a"].error
    assert fr.any_failed
    # The OTHER topics' results are byte-identical to their solo scans.
    for topic in ("fleet.b", "fleet.c"):
        assert fr.statuses[topic].status == "ok"
        assert _metrics_doc(fr.results[topic]) == solo_referee[topic]
    # The status table reports the poisoned topic.
    rollup = fr.rollup["fleet"]
    assert rollup["status_counts"]["failed"] == 1
    assert rollup["status_counts"]["ok"] == 2
    assert "error" in rollup["statuses"]["fleet.a"]
    from kafka_topic_analyzer_tpu.report import render_fleet_status

    table = render_fleet_status(fr.rollup)
    assert "failed" in table and "fleet.a" in table
    assert "unaffected" in table


# ---------------------------------------------------------------------------
# fleet follow: SIGTERM → per-topic checkpoints → resume


def test_fleet_follow_sigterm_checkpoint_resume(tmp_path, solo_referee):
    snap = str(tmp_path / "fleet-snaps")
    follow = FollowConfig(**dict(FAST_FOLLOW, checkpoint_every_s=0.0))
    phase1_total = N_PARTS * PHASE1_N

    def published(svc, topic):
        doc = svc.state.snapshot(topic)
        return doc["overall"]["count"] if doc else -1

    # Session 1: fold phase 1 of every topic, then SIGTERM.
    with _mk_broker(PHASE1) as broker:
        svc = _fleet_service(broker, follow=follow, snapshot_dir=snap)
        restore = svc.install_signal_handlers()
        try:
            killer = threading.Thread(
                target=lambda: (
                    _wait_for(
                        lambda: all(
                            published(svc, t) >= phase1_total for t in TOPICS
                        ),
                        what="phase-1 fleet reports",
                    ),
                    os.kill(os.getpid(), signal.SIGTERM),
                )
            )
            killer.start()
            fr1 = svc.run_follow()
            killer.join()
        finally:
            restore()
    assert svc._stop_reason == "SIGTERM"
    for t in TOPICS:
        assert fr1.results[t].metrics.overall_count == phase1_total
        # Per-topic checkpoint namespacing: one subdirectory per topic.
        assert os.path.exists(
            os.path.join(snap, t, "scan_snapshot.npz")
        )
    from kafka_topic_analyzer_tpu.checkpoint import list_topic_snapshots

    inventory = list_topic_snapshots(snap)
    assert set(inventory) == set(TOPICS)
    assert all(
        info["records_seen"] == phase1_total for info in inventory.values()
    )

    # Session 2: resume each topic from its checkpoint, tail phase 2.
    with _mk_broker(FULL) as broker:
        svc2 = _fleet_service(
            broker, follow=follow, snapshot_dir=snap, resume=True,
        )
        stopper = threading.Thread(
            target=lambda: (
                _wait_for(
                    lambda: all(
                        published(svc2, t) >= N_PARTS * FULL_N
                        for t in TOPICS
                    ),
                    what="resumed fleet reports",
                ),
                svc2.request_stop("test"),
            )
        )
        stopper.start()
        fr2 = svc2.run_follow()
        stopper.join()
    for t in TOPICS:
        assert _metrics_doc(fr2.results[t]) == solo_referee[t]


def test_cpu_backend_never_donates_state():
    """Concurrent per-topic scan threads + donated-state dispatch race
    XLA:CPU's donation bookkeeping: a live state buffer can be freed
    while still referenced, and the fold reads recycled heap memory
    (this surfaced as pointer-sized garbage in resumed fleet counts).
    On the host-CPU platform the backend must therefore compile its
    step WITHOUT donation; accelerators keep it."""
    backend = TpuBackend(_cfg(), init_now_s=10**10)
    if backend.device.platform == "cpu":
        assert backend._donate == ()
    else:
        assert backend._donate == (0,)


def test_fleet_follow_rediscovers_created_topic():
    follow = FollowConfig(**dict(FAST_FOLLOW))
    new_records = {0: _mk_records(42, 0, 0, 20)}
    with _mk_broker(PHASE1) as broker:
        bootstrap = f"127.0.0.1:{broker.port}"

        def rediscover():
            return [
                TopicSeed(name=d.name, partitions=d.partitions)
                for d in discover_topics(bootstrap)
            ]

        svc = _fleet_service(
            broker, follow=follow, rediscover=rediscover, rediscover_every=2,
        )

        def driver():
            _wait_for(
                lambda: svc.state.snapshot("fleet.a") is not None,
                what="initial fleet report",
            )
            broker.create_topic("fleet.created", new_records)
            _wait_for(
                lambda: (
                    svc.state.snapshot("fleet.created") is not None
                    and svc.state.snapshot("fleet.created")["overall"]["count"]
                    >= 20
                ),
                what="created-topic report",
            )
            svc.request_stop("test")

        t = threading.Thread(target=driver)
        t.start()
        fr = svc.run_follow()
        t.join()
    assert "fleet.created" in fr.results
    assert fr.results["fleet.created"].metrics.overall_count == 20
    assert fr.statuses["fleet.created"].status == "ok"


def test_fleet_batch_scans_all_topics_under_tight_dispatch_budget(
    solo_referee,
):
    """A dispatch-token budget smaller than the wave defers topics; a
    batch fleet must RE-OFFER the deferred remainder, not drop it — the
    default --dispatch-depth 2 against 3 topics hits exactly this."""
    with _mk_broker(FULL) as broker:
        svc = _fleet_service(
            broker, worker_budget=6, dispatch_budget=1, max_concurrent=3,
        )
        fr = svc.run_batch()
    assert set(fr.results) == set(TOPICS)
    for topic in TOPICS:
        assert fr.statuses[topic].status == "ok"
        assert _metrics_doc(fr.results[topic]) == solo_referee[topic]


def test_fleet_follow_stops_when_every_topic_failed():
    """Failure isolation needs survivors: an unreachable cluster fails
    every topic, and the follow loop must exit (reason 'all-failed')
    instead of polling a dead cluster forever."""
    scheduler = FleetScheduler(2, 2, 2)

    def dead_source(topic):
        raise OSError("connection refused")

    svc = FleetService(
        [TopicSeed(name=t, partitions=1) for t in ("a", "b")],
        dead_source,
        lambda t, parts, grant: None,
        64,
        scheduler,
        follow=FollowConfig(**FAST_FOLLOW),
    )
    t0 = time.monotonic()
    fr = svc.run_follow()
    assert time.monotonic() - t0 < 10.0
    assert svc._stop_reason == "all-failed"
    assert fr.any_failed
    assert all(s.status == "failed" for s in fr.statuses.values())
    assert fr.results == {}


def test_poll_failure_releases_held_grant():
    """A topic that fails during the watermark poll while HOLDING a
    grant must return its budget — otherwise every such failure shrinks
    the fleet's pool permanently."""
    scheduler = FleetScheduler(2, 2, 2)

    class _BoomSource:
        def partitions(self):
            return [0]

        def refresh_watermarks(self):
            raise OSError("broker gone")

    svc = FleetService(
        [TopicSeed("t", 1)],
        lambda t: _BoomSource(),
        lambda *a: None,
        64,
        scheduler,
        follow=FollowConfig(**FAST_FOLLOW),
    )
    scheduler.admit([TopicSeed("t", 1, lag=5)])
    assert scheduler.active == 1
    assert svc._poll_topic(svc.scans["t"]) == 0
    assert svc.scans["t"].status.status == "failed"
    assert scheduler.active == 0          # budget returned
    assert scheduler.workers_granted == 0


def test_backend_dispatch_depth_regrant_clamps_at_construction():
    """Rebalanced dispatch shares become a REAL backend bound between
    passes (shrink applies; grow clamps at the constructed depth, which
    sized the stager ring)."""
    backend = TpuBackend(
        _cfg(),
        dispatch=DispatchConfig(superbatch=2, depth=3),
        init_now_s=10**10,
    )
    assert backend.dispatch_depth == 3
    backend.set_dispatch_depth(1)
    assert backend.dispatch_depth == 1
    assert backend._queue.depth == 1
    backend.set_dispatch_depth(8)      # grow clamps at construction
    assert backend.dispatch_depth == 3
    assert backend._queue.depth == 3


def test_fleet_empty_topic_is_a_status_row():
    records = dict(FULL)
    records["fleet.empty"] = {p: [] for p in range(N_PARTS)}
    with _mk_broker(records) as broker:
        svc = _fleet_service(broker, topics=TOPICS + ["fleet.empty"])
        fr = svc.run_batch()
    assert fr.statuses["fleet.empty"].status == "empty"
    assert "fleet.empty" not in fr.results
    assert all(fr.statuses[t].status == "ok" for t in TOPICS)
    assert not fr.any_failed


# ---------------------------------------------------------------------------
# report surfaces: rollup + ?topic= routing


def test_report_json_topic_routing(solo_referee):
    from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter

    exporter = PrometheusExporter(0)
    base = f"http://127.0.0.1:{exporter.port}/report.json"
    try:
        serve_state.set_active(None)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base, timeout=5)
        assert exc.value.code == 404

        with _mk_broker(FULL) as broker:
            svc = _fleet_service(broker)
            serve_state.set_active(svc.state)
            # Before any publish: rollup 503, unknown topic 404.
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base, timeout=5)
            assert exc.value.code == 503
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "?topic=nope", timeout=5)
            assert exc.value.code == 404
            fr = svc.run_batch()
        # Bare /report.json = the cluster rollup.
        with urllib.request.urlopen(base, timeout=5) as resp:
            rollup = json.loads(resp.read())
        assert rollup["fleet"]["topics"] == len(TOPICS)
        assert set(rollup["fleet"]["statuses"]) == set(TOPICS)
        # ?topic= = that topic's solo-schema document.
        for topic in TOPICS:
            with urllib.request.urlopen(
                base + f"?topic={topic}", timeout=5
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["topic"] == topic
            assert doc["overall"] == solo_referee[topic]["overall"]
            assert doc["partitions"] == solo_referee[topic]["partitions"]
            assert doc["fleet"]["status"] == "ok"
        # Unknown topic still 404s after publishes.
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "?topic=nope", timeout=5)
        assert exc.value.code == 404
        assert fr.rollup["fleet"]["totals"]["records"] == sum(
            d["overall"]["count"] for d in solo_referee.values()
        )
    finally:
        serve_state.set_active(None)
        exporter.close()


# ---------------------------------------------------------------------------
# CLI surface


def test_cli_fleet_json(capsys, solo_referee):
    from kafka_topic_analyzer_tpu import cli

    with _mk_broker(FULL) as broker:
        rc = cli.main([
            "-t", "*", "--fleet", "-b", f"127.0.0.1:{broker.port}",
            "--librdkafka", "retry.backoff.ms=5,reconnect.backoff.max.ms=40",
            "-c", "--distinct-keys",
            "--json", "--quiet",
        ])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(doc["fleet"]["statuses"]) == set(TOPICS)  # internal excluded
    assert set(doc["topics"]) == set(TOPICS)
    for topic in TOPICS:
        assert (
            doc["topics"][topic]["overall"]["count"]
            == solo_referee[topic]["overall"]["count"]
        )
        assert doc["topics"][topic]["fleet"]["status"] == "ok"


def test_cli_fleet_exclude_globs(capsys):
    from kafka_topic_analyzer_tpu import cli

    with _mk_broker(FULL) as broker:
        rc = cli.main([
            "-t", "fleet.*", "--fleet", "--fleet-exclude", "*.b,*.c",
            "-b", f"127.0.0.1:{broker.port}",
            "--librdkafka", "retry.backoff.ms=5",
            "--json", "--quiet",
        ])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert list(doc["fleet"]["statuses"]) == ["fleet.a"]


def test_cli_multi_topic_follow_lifted(capsys):
    """The PR-11 rejection is gone: an explicit topic list under --follow
    runs through the fleet scheduler, each topic solo-identical."""
    from kafka_topic_analyzer_tpu import cli

    with _mk_broker(FULL) as broker:
        rc = cli.main([
            "-t", "fleet.a,fleet.b", "-b", f"127.0.0.1:{broker.port}",
            "--librdkafka", "retry.backoff.ms=5,reconnect.backoff.max.ms=40",
            "--follow", "--follow-idle-exit", "0.2",
            "--poll-interval", "0.02",
            "--json", "--quiet",
        ])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(doc["fleet"]["statuses"]) == {"fleet.a", "fleet.b"}
    for topic in ("fleet.a", "fleet.b"):
        assert (
            doc["topics"][topic]["overall"]["count"] == N_PARTS * FULL_N
        )


def test_cli_fleet_rejections_name_the_lifting_flag(capsys):
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "*", "--fleet", "-b", "127.0.0.1:1", "--mesh", "2",
    ])
    assert rc == 1
    err = capsys.readouterr().err
    assert "--fleet does not support --mesh" in err
    assert "solo" in err  # names the path that lifts the restriction

    rc = cli.main([
        "-t", "*", "--fleet", "-b", "127.0.0.1:1", "--source", "synthetic",
    ])
    assert rc == 1
    assert "--fleet requires --source kafka" in capsys.readouterr().err


def test_fleet_admissions_are_booked():
    """Rule-10 contract, dynamically: a fleet run leaves a reconstructible
    admission trace on kta_fleet_admissions_total."""
    from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

    def count(reason):
        return obs_metrics.FLEET_ADMISSIONS.labels(
            reason=reason, instance="solo"
        ).value

    seed0 = count("admitted-seed")
    released0 = count("released")
    with _mk_broker(FULL) as broker:
        svc = _fleet_service(broker)
        svc.run_batch()
    assert count("admitted-seed") - seed0 == len(TOPICS)
    assert count("released") - released0 == len(TOPICS)
