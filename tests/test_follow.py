"""Follow mode (ISSUE 11): the long-running analyzer service.

The contract under test, per DESIGN.md §18:

- BYTE-IDENTITY: a followed topic stopped at offset X reports exactly
  what a batch scan to X reports — across ingest workers × superbatch K
  × mesh, with records arriving mid-follow (FakeBroker.produce);
- DURABILITY: SIGTERM lands a final checkpoint and a clean exit, and a
  restarted service resumes from any snapshot (batch- or follow-written)
  with no loss and no double-count;
- SERVICE SURFACE: /report.json serves the latest poll-boundary snapshot
  (same schema as --json) while folding continues, without touching the
  drive loop;
- WINDOW ALGEBRA: ring states merge associatively/commutatively, and the
  observer never perturbs the batches it watches;
- HEAD BEHAVIOR: watermark refreshes ride the retry budget (a metadata
  hiccup never kills the service), lag gauges track the MOVING head, and
  an idle service does not flood the event log.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    DispatchConfig,
    FollowConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.serve import state as serve_state
from kafka_topic_analyzer_tpu.serve.follow import FollowService
from kafka_topic_analyzer_tpu.serve.windows import (
    WindowObserver,
    WindowRing,
    WindowState,
)

from fake_broker import FakeBroker, FakeCluster, FaultInjector

pytestmark = pytest.mark.follow

TOPIC = "follow.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}

#: Tight service pacing so follow tests stay inside the tier-1 budget.
FAST_FOLLOW = dict(
    poll_interval_s=0.02,
    idle_backoff_max_s=0.05,
    window_secs=5.0,
    window_count=4,
)

N_PARTS = 3
PHASE1_N = 120
PHASE2_N = 60


def _mk_records(partition: int, lo: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 23}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(lo, lo + n)
    ]


PHASE1 = {p: _mk_records(p, 0, PHASE1_N) for p in range(N_PARTS)}
PHASE2 = {p: _mk_records(p, PHASE1_N, PHASE2_N) for p in range(N_PARTS)}
FULL = {p: PHASE1[p] + PHASE2[p] for p in range(N_PARTS)}
TOTAL = N_PARTS * (PHASE1_N + PHASE2_N)


def _cfg(**kw) -> AnalyzerConfig:
    base = dict(
        num_partitions=N_PARTS,
        batch_size=64,
        count_alive_keys=True,
        alive_bitmap_bits=16,
        enable_hll=True,
        hll_p=8,
        enable_quantiles=True,
        quantiles_per_partition=True,
    )
    base.update(kw)
    return AnalyzerConfig(**base)


def _metrics_doc(result) -> dict:
    return result.metrics.to_dict(result.start_offsets, result.end_offsets)


def _batch_scan(records, backend_factory, workers=1, batch_size=64):
    with FakeBroker(TOPIC, records, max_records_per_fetch=48) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        result = run_scan(
            TOPIC, src, backend_factory(), batch_size,
            ingest_workers=workers,
        )
        src.close()
    return result


def _wait_for(predicate, timeout_s=20.0, interval_s=0.01, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


def _published_count(svc) -> int:
    doc = svc.state.snapshot()
    return doc["overall"]["count"] if doc else -1


def _run_followed(
    backend,
    workers=1,
    batch_size=64,
    follow_kw=None,
    snapshot_dir=None,
    resume=False,
    mid_follow=None,
    stop_at=TOTAL,
):
    """Drive one follow session: serve PHASE1, wait for it to be folded
    and published, produce PHASE2 (after the optional ``mid_follow`` hook
    armed chaos), wait for ``stop_at`` records, stop, return the result."""
    follow = FollowConfig(**dict(FAST_FOLLOW, **(follow_kw or {})))
    with FakeBroker(TOPIC, PHASE1, max_records_per_fetch=48) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        svc = FollowService(
            TOPIC, src, backend, batch_size, follow,
            snapshot_dir=snapshot_dir, resume=resume,
            ingest_workers=workers,
        )
        errors = []

        def driver():
            try:
                _wait_for(
                    lambda: _published_count(svc) >= N_PARTS * PHASE1_N,
                    what="phase-1 report",
                )
                if mid_follow is not None:
                    mid_follow(broker)
                for p in range(N_PARTS):
                    broker.produce(p, PHASE2[p])
                _wait_for(
                    lambda: _published_count(svc) >= stop_at,
                    what="phase-2 report",
                )
            except BaseException as e:  # surfaced after join
                errors.append(e)
            finally:
                svc.request_stop("test")

        t = threading.Thread(target=driver)
        t.start()
        result = svc.run()
        t.join()
        src.close()
        if errors:
            raise errors[0]
    return result, svc


# ---------------------------------------------------------------------------
# byte-identity: followed-to-X == batch-to-X, across workers × K × mesh


@pytest.fixture(scope="module")
def batch_referee():
    """Batch scan of the full topic — the byte-exact referee."""
    return _metrics_doc(
        _batch_scan(FULL, lambda: TpuBackend(_cfg(), init_now_s=10**10))
    )


@pytest.mark.parametrize("workers,superbatch", [
    (1, 1), (4, 1), (1, 4), (4, 4),
])
def test_follow_byte_identity_matrix(batch_referee, workers, superbatch):
    backend = TpuBackend(
        _cfg(), init_now_s=10**10,
        dispatch=DispatchConfig(superbatch=superbatch),
    )
    result, svc = _run_followed(backend, workers=workers)
    assert _metrics_doc(result) == batch_referee
    assert svc.passes >= 2  # initial catch-up + at least one tail pass
    assert result.next_offsets == {
        p: PHASE1_N + PHASE2_N for p in range(N_PARTS)
    }


@pytest.mark.parametrize("superbatch", [1, 4])
def test_follow_sharded_mesh_identity(batch_referee, superbatch):
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    backend = ShardedTpuBackend(
        _cfg(mesh_shape=(2, 1)),
        dispatch=DispatchConfig(superbatch=superbatch),
        init_now_s=10**10,
    )
    result, _ = _run_followed(backend, workers=2)
    assert _metrics_doc(result) == batch_referee


def test_follow_cpu_oracle_identity():
    ref = _metrics_doc(
        _batch_scan(FULL, lambda: CpuExactBackend(_cfg(), init_now_s=10**10))
    )
    result, _ = _run_followed(CpuExactBackend(_cfg(), init_now_s=10**10))
    assert _metrics_doc(result) == ref


def test_follow_chaos_leader_migration_and_faults(batch_referee):
    """Transport chaos mid-follow: the tail passes recover exactly."""
    follow = FollowConfig(**FAST_FOLLOW)
    with FakeCluster(TOPIC, PHASE1, n_nodes=2, max_records_per_fetch=48) as cluster:
        src = KafkaWireSource(
            cluster.bootstrap, TOPIC, overrides=dict(FAST_RETRY)
        )
        backend = TpuBackend(_cfg(), init_now_s=10**10)
        svc = FollowService(TOPIC, src, backend, 64, follow)
        errors = []

        def driver():
            try:
                _wait_for(
                    lambda: _published_count(svc) >= N_PARTS * PHASE1_N,
                    what="phase-1 report",
                )
                # Arm chaos, then produce the tail into it: partition 0
                # migrates leader, node 1 drops a response mid-stream.
                cluster.migrate_leader(0, 1)
                cluster.nodes[1].faults = FaultInjector().drop_connection(
                    64, times=1
                )
                for node in cluster.nodes:
                    for p in range(N_PARTS):
                        node.produce(p, PHASE2[p])
                _wait_for(
                    lambda: _published_count(svc) >= TOTAL,
                    what="phase-2 report",
                )
            except BaseException as e:
                errors.append(e)
            finally:
                svc.request_stop("test")

        t = threading.Thread(target=driver)
        t.start()
        result = svc.run()
        t.join()
        src.close()
        if errors:
            raise errors[0]
    assert _metrics_doc(result) == batch_referee
    assert result.degraded_partitions == {}


# ---------------------------------------------------------------------------
# durability: SIGTERM → checkpoint → restart → resume


def test_sigterm_checkpoint_resume_roundtrip(tmp_path, batch_referee):
    snap = str(tmp_path / "snaps")
    follow = FollowConfig(**dict(FAST_FOLLOW, checkpoint_every_s=0.0))
    # Session 1: fold phase 1, then SIGTERM from a helper thread — the
    # handler requests a stop, the loop commits a final checkpoint and
    # returns cleanly.
    with FakeBroker(TOPIC, PHASE1, max_records_per_fetch=48) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        svc = FollowService(
            TOPIC, src, TpuBackend(_cfg(), init_now_s=10**10), 64, follow,
            snapshot_dir=snap,
        )
        restore = svc.install_signal_handlers()
        try:
            killer = threading.Thread(
                target=lambda: (
                    _wait_for(
                        lambda: _published_count(svc) >= N_PARTS * PHASE1_N,
                        what="phase-1 report",
                    ),
                    os.kill(os.getpid(), signal.SIGTERM),
                )
            )
            killer.start()
            result1 = svc.run()
            killer.join()
        finally:
            restore()
        src.close()
    assert result1.metrics.overall_count == N_PARTS * PHASE1_N
    assert svc._stop_reason == "SIGTERM"
    assert os.path.exists(os.path.join(snap, "scan_snapshot.npz"))
    # The metadata-only reader sees the final-checkpoint commit point.
    from kafka_topic_analyzer_tpu.checkpoint import snapshot_info

    info = snapshot_info(snap)
    assert info["records_seen"] == N_PARTS * PHASE1_N
    assert info["next_offsets"] == {
        str(p): PHASE1_N for p in range(N_PARTS)
    }

    # Session 2: a fresh process-equivalent resumes from the checkpoint,
    # tails phase 2, and the union must equal the batch referee — no
    # record lost, none double-counted.
    with FakeBroker(TOPIC, FULL, max_records_per_fetch=48) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        svc2 = FollowService(
            TOPIC, src, TpuBackend(_cfg(), init_now_s=10**10), 64, follow,
            snapshot_dir=snap, resume=True,
        )
        stopper = threading.Thread(
            target=lambda: (
                _wait_for(
                    lambda: _published_count(svc2) >= TOTAL,
                    what="resumed full report",
                ),
                svc2.request_stop("test"),
            )
        )
        stopper.start()
        result2 = svc2.run()
        stopper.join()
        src.close()
    assert _metrics_doc(result2) == batch_referee


def test_follow_resumes_batch_scan_snapshot(tmp_path, batch_referee):
    """A snapshot written by a plain batch scan seeds a follow service —
    the fingerprint doesn't know (or care) which mode wrote it."""
    snap = str(tmp_path / "snaps")
    with FakeBroker(TOPIC, PHASE1, max_records_per_fetch=48) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        run_scan(
            TOPIC, src, TpuBackend(_cfg(), init_now_s=10**10), 64,
            snapshot_dir=snap, snapshot_every_s=0.0,
        )
        src.close()
    with FakeBroker(TOPIC, FULL, max_records_per_fetch=48) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        svc = FollowService(
            TOPIC, src, TpuBackend(_cfg(), init_now_s=10**10), 64,
            FollowConfig(**FAST_FOLLOW), snapshot_dir=snap, resume=True,
        )
        stopper = threading.Thread(
            target=lambda: (
                _wait_for(
                    lambda: _published_count(svc) >= TOTAL,
                    what="resumed full report",
                ),
                svc.request_stop("test"),
            )
        )
        stopper.start()
        result = svc.run()
        stopper.join()
        src.close()
    assert _metrics_doc(result) == batch_referee


# ---------------------------------------------------------------------------
# service surface: /report.json under concurrent folding


def test_report_json_served_while_folding(batch_referee):
    from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter

    exporter = PrometheusExporter(0)
    url = f"http://127.0.0.1:{exporter.port}/report.json"
    try:
        # No service active → 404 with a hint.
        serve_state.set_active(None)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url, timeout=5)
        assert exc.value.code == 404

        scraped = []

        def mid(broker):
            # Service is live (svc.run registered its state) and mid-fold:
            # the endpoint must answer from the published snapshot without
            # blocking on — or being blocked by — the drive loop.
            t0 = time.monotonic()
            with urllib.request.urlopen(url, timeout=5) as resp:
                doc = json.loads(resp.read())
            scraped.append((time.monotonic() - t0, doc))

        result, svc = _run_followed(
            TpuBackend(_cfg(), init_now_s=10**10), mid_follow=mid
        )
        assert _metrics_doc(result) == batch_referee
        elapsed, doc = scraped[0]
        # The handler reads one pre-serialized snapshot: far under the
        # 100 ms assembly bar even on a loaded CI box.
        assert elapsed < 1.0
        assert doc["topic"] == TOPIC
        assert doc["overall"]["count"] >= N_PARTS * PHASE1_N
        assert "follow" in doc and "windows" in doc and "flight" in doc
        assert set(doc["follow"]["next_offsets"]) == {
            str(p) for p in range(N_PARTS)
        }
        # Final published report equals the CLI's --json schema essentials.
        final = svc.state.snapshot()
        assert final["overall"]["count"] == TOTAL
        # Windows describe the LIVE tail: the phase-1 catch-up backlog is
        # deliberately excluded (it did not "change in the last N
        # minutes"); only the records produced mid-follow are windowed.
        assert final["windows"]["merged"]["records"] == N_PARTS * PHASE2_N
        # Published totals are SERVICE totals, not last-pass totals: the
        # cumulative duration rides every snapshot.
        assert final["duration_secs"] == result.duration_secs
    finally:
        serve_state.set_active(None)
        exporter.close()


# ---------------------------------------------------------------------------
# window-ring algebra


def _rand_batch(rng, n=64, parts=N_PARTS):
    sizes = rng.integers(0, 500, n)
    key_null = rng.random(n) < 0.2
    return RecordBatch(
        partition=rng.integers(0, parts, n).astype(np.int32),
        key_len=np.where(key_null, 0, rng.integers(1, 20, n)).astype(np.int32),
        value_len=sizes.astype(np.int32),
        key_null=key_null,
        value_null=rng.random(n) < 0.1,
        ts_s=np.full(n, 1_600_000_000, dtype=np.int64),
        key_hash32=rng.integers(0, 2**32, n, dtype=np.uint32),
        key_hash64=rng.integers(0, 2**63, n, dtype=np.uint64),
        valid=rng.random(n) < 0.95,
    )


def _state_tuple(st: WindowState):
    return (
        st.records.tolist(), st.bytes.tolist(), st.tombstones.tolist(),
        st.hll.tolist(), st.size_hist.tolist(),
    )


def test_window_state_merge_algebra():
    rng = np.random.default_rng(7)
    rows = lambda b: b.partition.astype(np.int64)  # noqa: E731
    states = []
    for _ in range(3):
        st = WindowState(N_PARTS, hll_p=6)
        for _ in range(4):
            b = _rand_batch(rng)
            st.observe(rows(b), b)
        states.append(st)
    a, b, c = states
    # Associative + commutative.
    assert _state_tuple(a.merge(b).merge(c)) == _state_tuple(
        a.merge(b.merge(c))
    )
    assert _state_tuple(a.merge(b)) == _state_tuple(b.merge(a))
    # A fresh state is the merge identity.
    ident = WindowState(N_PARTS, hll_p=6)
    assert _state_tuple(a.merge(ident)) == _state_tuple(a)
    # Splitting a stream across states then merging == one-state fold.
    rng1, rng2 = np.random.default_rng(11), np.random.default_rng(11)
    whole = WindowState(N_PARTS, hll_p=6)
    parts_a, parts_b = WindowState(N_PARTS, hll_p=6), WindowState(N_PARTS, hll_p=6)
    for i in range(6):
        batch = _rand_batch(rng1)
        whole.observe(rows(batch), batch)
        again = _rand_batch(rng2)
        (parts_a if i % 2 else parts_b).observe(rows(again), again)
    assert _state_tuple(whole) == _state_tuple(parts_a.merge(parts_b))


def test_window_ring_rotation_and_merge():
    now = [0.0]
    ring = WindowRing(
        [0, 1, 2], window_secs=10.0, window_count=3, hll_p=6,
        clock=lambda: now[0],
    )
    rng = np.random.default_rng(3)
    b1 = _rand_batch(rng)
    ring.observe_batch(b1)
    now[0] = 11.0  # next window
    b2 = _rand_batch(rng)
    ring.observe_batch(b2)
    rep = ring.report()
    assert [w["window"] for w in rep["windows"]] == [0, 1]
    total = int(b1.valid.sum() + b2.valid.sum())
    assert rep["merged"]["records"] == total
    assert sum(w["records"] for w in rep["windows"]) == total
    # Ring bound: after 5 more windows only the newest 3 survive.
    for wi in range(2, 7):
        now[0] = wi * 10.0 + 1
        ring.observe_batch(_rand_batch(rng))
    rep = ring.report()
    assert len(rep["windows"]) == 3
    assert [w["window"] for w in rep["windows"]] == [4, 5, 6]
    # Cardinality estimates land within the sketch's error regime.
    merged = ring.merged()
    est = sum(merged.cardinality())
    assert est > 0


def test_window_ring_prunes_by_index_distance_across_quiet_gaps():
    """Quiet periods create no states, so the ring must prune by window
    INDEX, not insertion count — a burst from hours ago cannot linger in
    'the last N windows', and the merged rate denominator is the ring's
    covered span (quiet windows included), not just the populated ones."""
    now = [0.0]
    ring = WindowRing(
        [0, 1, 2], window_secs=10.0, window_count=3, hll_p=6,
        clock=lambda: now[0],
    )
    rng = np.random.default_rng(9)
    burst = _rand_batch(rng)
    ring.observe_batch(burst)
    # Long silence, then one batch far in the future: the old burst has
    # aged out of the 3-window horizon entirely.
    now[0] = 101.0
    fresh = _rand_batch(rng)
    ring.observe_batch(fresh)
    rep = ring.report()
    assert [w["window"] for w in rep["windows"]] == [10]
    assert rep["merged"]["records"] == int(fresh.valid.sum())
    # Coverage clamps to the ring horizon — NOT the sum of populated
    # windows (which would claim a ~10x rate across the quiet gap).
    assert ring.coverage_s() == pytest.approx(30.0)
    assert rep["merged"]["rate_per_s"] == pytest.approx(
        int(fresh.valid.sum()) / 30.0, rel=1e-6
    )


def test_follow_rejects_multi_controller_backend():
    """Multi-controller pass entry would need per-poll lockstep
    agreement; until ROADMAP item 2 builds it, refuse cleanly."""
    class _Cfg:
        data_shards = 2

    class _MultiBackend:
        config = _Cfg()
        local_rows = [0]  # this process hosts 1 of 2 data rows

        def global_any(self, flag):  # pragma: no cover - presence only
            return flag

    class _Src:
        def partitions(self):
            return [0, 1]

    with pytest.raises(ValueError, match="multi-controller"):
        FollowService("t", _Src(), _MultiBackend(), 64, FollowConfig())


def test_window_observer_passes_batches_through_untouched():
    class _Src:
        def partitions(self):
            return [0, 1, 2]

        def batches(self, batch_size, partitions=None, start_at=None):
            rng = np.random.default_rng(5)
            for _ in range(3):
                yield _rand_batch(rng)

    ring = WindowRing([0, 1, 2], window_secs=60, window_count=2, hll_p=6)
    obs = WindowObserver(_Src(), ring)
    seen = list(obs.batches(64))
    rng = np.random.default_rng(5)
    expect = [_rand_batch(rng) for _ in range(3)]
    for got, want in zip(seen, expect):
        for name, _ in RecordBatch.FIELDS:
            np.testing.assert_array_equal(
                getattr(got, name), getattr(want, name)
            )
    assert ring.merged().records.sum() == sum(b.valid.sum() for b in expect)


# ---------------------------------------------------------------------------
# head behavior: watermark-refresh hardening, lag gauges, event flood


def test_watermark_refresh_survives_broker_outage():
    overrides = dict(FAST_RETRY, **{"transport.retry.budget": "2"})
    broker = FakeBroker(TOPIC, PHASE1).start()
    src = KafkaWireSource(
        f"127.0.0.1:{broker.port}", TOPIC, overrides=overrides
    )
    start0, end0 = src.watermarks()
    fails0 = obs_metrics.WATERMARK_REFRESH_FAILURES.value
    broker.kill()  # dead broker: every re-poll attempt fails
    start, end = src.refresh_watermarks()
    # Budget exhausted → the PREVIOUS snapshot stays in force, the
    # give-up is booked, and no exception reaches the service loop.
    assert (start, end) == (start0, end0)
    assert obs_metrics.WATERMARK_REFRESH_FAILURES.value == fails0 + 1
    src.close()
    broker.stop()


def test_watermark_refresh_sees_moving_head():
    with FakeBroker(TOPIC, PHASE1) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        _, end0 = src.watermarks()
        assert end0 == {p: PHASE1_N for p in range(N_PARTS)}
        broker.produce(0, PHASE2[0])
        # The cached batch-scan snapshot must NOT move...
        assert src.watermarks()[1] == end0
        # ...until the follow loop explicitly refreshes it.
        _, end1 = src.refresh_watermarks()
        assert end1[0] == PHASE1_N + PHASE2_N
        assert src.watermarks()[1] == end1
        src.close()


def test_follow_lifecycle_events_do_not_flood():
    events = []
    sink = lambda etype, fields: events.append((etype, fields))  # noqa: E731
    obs_events.add_sink(sink)
    try:
        result, svc = _run_followed(
            CpuExactBackend(_cfg(), init_now_s=10**10),
            follow_kw=dict(poll_interval_s=0.005, idle_backoff_max_s=0.01),
        )
    finally:
        obs_events.remove_sink(sink)
    kinds = [e for e, _ in events]
    # ONE lifecycle pair for the whole service run, not one per pass.
    assert kinds.count("scan_start") == 1
    assert kinds.count("scan_end") == 1
    assert kinds.count("follow_stop") == 1
    starts = [f for e, f in events if e == "scan_start"]
    assert starts[0]["follow"] is True
    # follow_poll only fires on productive polls — never once per idle
    # poll, however many the head-idle period racked up.
    polls = [f for e, f in events if e == "follow_poll"]
    assert 1 <= len(polls) <= svc.passes
    assert all(f["new_records"] > 0 for f in polls)
    # The shared heartbeat limiter spans passes: a sub-interval service
    # run emits at most the first-ready heartbeat plus the closing one.
    assert kinds.count("heartbeat") <= 2
    # Lag gauges settle at zero against the FINAL head, not the start
    # snapshot.
    assert obs_metrics.FOLLOW_LAG.value == 0


def test_follow_empty_topic_waits_for_first_record():
    empty = {p: [] for p in range(N_PARTS)}
    follow = FollowConfig(**FAST_FOLLOW)
    with FakeBroker(TOPIC, empty, max_records_per_fetch=48) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        assert src.is_empty()
        svc = FollowService(
            TOPIC, src, CpuExactBackend(_cfg(), init_now_s=10**10), 64,
            follow,
        )

        def driver():
            _wait_for(lambda: svc.state.snapshot() is not None,
                      what="empty initial report")
            broker.produce(0, PHASE2[0])
            _wait_for(lambda: _published_count(svc) >= PHASE2_N,
                      what="first records")
            svc.request_stop("test")

        t = threading.Thread(target=driver)
        t.start()
        result = svc.run()
        t.join()
        src.close()
    assert result.metrics.overall_count == PHASE2_N
    assert result.next_offsets[0] == PHASE1_N + PHASE2_N


def test_follow_idle_exit_drains_and_stops():
    """--follow-idle-exit: catch up, wait out the idle window, exit on
    its own — no driver thread involved."""
    follow = FollowConfig(
        **dict(FAST_FOLLOW, idle_exit_s=0.15)
    )
    with FakeBroker(TOPIC, PHASE1, max_records_per_fetch=48) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC, overrides=dict(FAST_RETRY)
        )
        svc = FollowService(
            TOPIC, src, CpuExactBackend(_cfg(), init_now_s=10**10), 64,
            follow,
        )
        result = svc.run()
        src.close()
    assert svc._stop_reason == "idle"
    assert result.metrics.overall_count == N_PARTS * PHASE1_N


# ---------------------------------------------------------------------------
# CLI surface


def test_cli_follow_json(capsys):
    with FakeBroker(TOPIC, FULL, max_records_per_fetch=48) as broker:
        rc = __import__(
            "kafka_topic_analyzer_tpu.cli", fromlist=["main"]
        ).main([
            "-t", TOPIC, "-b", f"127.0.0.1:{broker.port}",
            "--librdkafka", "retry.backoff.ms=5,reconnect.backoff.max.ms=40",
            "--follow", "--follow-idle-exit", "0.15",
            "--poll-interval", "0.02", "--window-secs", "5",
            "--json", "--quiet",
        ])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["overall"]["count"] == TOTAL
    assert doc["follow"]["passes"] >= 1
    assert doc["follow"]["next_offsets"] == {
        str(p): PHASE1_N + PHASE2_N for p in range(N_PARTS)
    }
    # Everything was already retained at service start, so it ALL folded
    # in the catch-up pass — and catch-up records are excluded from the
    # live-tail windows by design.
    assert doc["windows"]["merged"]["records"] == 0
    assert doc["telemetry"]["kta_follow_polls_total"]["samples"][0]["value"] >= 1


def test_cli_follow_multi_topic_routes_to_fleet(capsys):
    """The PR-11 multi-topic rejection is LIFTED: '-t a,b --follow' now
    runs through the fleet scheduler (tests/test_fleet.py proves the
    happy path).  Against an unreachable cluster every topic fails in
    isolation and the fleet exits 1 — it does not poll a dead cluster
    forever, and it does not print the old rejection."""
    from kafka_topic_analyzer_tpu import cli

    rc = cli.main([
        "-t", "a,b", "-b", "127.0.0.1:1", "--follow", "--source", "kafka",
        "--librdkafka", "retry.backoff.ms=1,reconnect.backoff.max.ms=5",
        "--quiet",
    ])
    assert rc == 1
    err = capsys.readouterr().err
    assert "--follow does not support multi-topic" not in err
