"""Synthetic source: determinism, slicing, and key scheme invariants."""

import numpy as np

from kafka_topic_analyzer_tpu.io.synthetic import (
    SyntheticSource,
    SyntheticSpec,
    synth_fields,
    synth_key_bytes,
)
from kafka_topic_analyzer_tpu.ops.fnv import fnv1a32_ref, fnv1a64
from kafka_topic_analyzer_tpu.records import RecordBatch

SPEC = SyntheticSpec(
    num_partitions=4,
    messages_per_partition=1000,
    keys_per_partition=50,
    key_null_permille=100,
    tombstone_permille=200,
    value_len_min=10,
    value_len_max=30,
    seed=42,
)


def test_watermarks_and_order():
    src = SyntheticSource(SPEC)
    start, end = src.watermarks()
    assert start == {p: 0 for p in range(4)}
    assert end == {p: 1000 for p in range(4)}
    batches = list(src.batches(batch_size=256))
    total = sum(len(b) for b in batches)
    assert total == 4000
    # Per-partition offsets strictly increasing across the whole stream.
    full = RecordBatch.concat(batches)
    for p in range(4):
        ts = full.ts_s[full.partition == p]
        assert np.all(np.diff(ts) >= 0)


def test_deterministic_and_batch_size_invariant():
    src = SyntheticSource(SPEC)
    a = RecordBatch.concat(list(src.batches(batch_size=100)))
    b = RecordBatch.concat(list(src.batches(batch_size=999)))
    for name, _ in RecordBatch.FIELDS:
        assert np.array_equal(getattr(a, name), getattr(b, name)), name


def test_partition_slicing_matches_full_stream():
    src = SyntheticSource(SPEC)
    full = RecordBatch.concat(list(src.batches(batch_size=512)))
    for shard in ([0, 2], [1], [3]):
        sliced = RecordBatch.concat(list(src.batches(batch_size=512, partitions=shard)))
        mask = np.isin(full.partition, shard)
        # Same multiset per partition; compare sorted by (partition, ts).
        def key(b):
            return np.lexsort((b.ts_s, b.partition))

        fsel = full.take(np.nonzero(mask)[0])
        fi, si = key(fsel), key(sliced)
        for name, _ in RecordBatch.FIELDS:
            assert np.array_equal(
                getattr(fsel, name)[fi], getattr(sliced, name)[si]
            ), name


def test_key_hashes_match_scalar_reference():
    part = np.array([0, 1, 2, 3, 0], dtype=np.int64)
    off = np.array([0, 1, 2, 3, 999], dtype=np.int64)
    f = synth_fields(SPEC, part, off)
    # Recompute key ids the way the generator derives them, then check the
    # hashes against the scalar fnv implementations on the key bytes.
    from kafka_topic_analyzer_tpu.ops.fnv import splitmix64

    for i in range(len(part)):
        stream = splitmix64(SPEC.seed ^ (int(part[i]) << 40))
        x = splitmix64((stream + int(off[i]) * 0x9E3779B97F4A7C15) & (2**64 - 1))
        if x % 1000 < SPEC.key_null_permille:
            assert f["key_hash32"][i] == 0
            continue
        local = (x >> 20) % SPEC.keys_per_partition
        key_id = int(part[i]) + SPEC.num_partitions * local
        kb = synth_key_bytes(SPEC, key_id)
        assert len(kb) == SPEC.key_len
        assert int(f["key_hash32"][i]) == fnv1a32_ref(kb)
        assert int(f["key_hash64"][i]) == fnv1a64(kb)


def test_nearby_seeds_give_different_topics():
    """Regression: seed and seed+1 must not produce permutations of the
    same record multiset (the old seed^offset derivation did)."""
    import dataclasses

    a = RecordBatch.concat(list(SyntheticSource(SPEC).batches(4096)))
    b = RecordBatch.concat(
        list(
            SyntheticSource(dataclasses.replace(SPEC, seed=SPEC.seed + 1)).batches(4096)
        )
    )
    assert int(a.value_len.sum()) != int(b.value_len.sum())
    assert int(a.key_null.sum()) != int(b.key_null.sum())


def test_keys_are_partition_disjoint():
    src = SyntheticSource(SPEC)
    full = RecordBatch.concat(list(src.batches(batch_size=4096)))
    keyed = ~full.key_null
    seen = {}
    for p, h in zip(full.partition[keyed].tolist(), full.key_hash64[keyed].tolist()):
        assert seen.setdefault(h, p) == p, "key hash seen in two partitions"
