"""Small utility coverage: env_logger-style level parsing, spinner, mesh
partition assignment, profiling counters."""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.parallel.mesh import assign_partitions
from kafka_topic_analyzer_tpu.utils.profiling import ScanProfile
from kafka_topic_analyzer_tpu.utils.progress import Spinner
from kafka_topic_analyzer_tpu.utils.timefmt import format_utc_seconds


def test_log_level_parsing():
    import logging

    from kafka_topic_analyzer_tpu.utils.log import parse_level

    assert parse_level("debug") == logging.DEBUG
    assert parse_level("warn") == logging.WARNING
    assert parse_level("module=debug,info") == logging.INFO  # first bare seg
    assert parse_level("nonsense") == logging.ERROR          # fallback
    assert parse_level("trace") == logging.DEBUG
    assert parse_level("off") == logging.CRITICAL


def test_log_spec_per_target_levels():
    import logging

    from kafka_topic_analyzer_tpu.utils.log import parse_spec

    assert parse_spec("warn") == (logging.WARNING, {})
    assert parse_spec("kta.io=debug,error") == (
        logging.ERROR, {"kta.io": logging.DEBUG}
    )
    # Junk segments are ignored; a spec with no usable default → ERROR.
    assert parse_spec("garbage,=debug,kta.io=loud") == (logging.ERROR, {})
    assert parse_spec("") == (logging.ERROR, {})
    # Junk around a good target doesn't poison it.
    assert parse_spec("nope,kta=trace") == (
        logging.ERROR, {"kta": logging.DEBUG}
    )


def test_log_target_alias_resolution():
    from kafka_topic_analyzer_tpu.utils.log import resolve_target

    assert resolve_target("kta") == "kafka_topic_analyzer_tpu"
    assert resolve_target("kta.io") == "kafka_topic_analyzer_tpu.io"
    assert resolve_target("ktax.io") == "ktax.io"  # no false prefix match
    assert resolve_target("other.mod") == "other.mod"


def test_init_logging_configures_named_loggers(monkeypatch):
    import logging

    from kafka_topic_analyzer_tpu.utils.log import init_logging

    monkeypatch.setenv("KTA_LOG", "warn,kta.io=debug")
    io_logger = logging.getLogger("kafka_topic_analyzer_tpu.io")
    old_level = io_logger.level
    try:
        init_logging()
        assert io_logger.level == logging.DEBUG
        # Hierarchy: module loggers under the target inherit its level.
        child = logging.getLogger("kafka_topic_analyzer_tpu.io.kafka_wire")
        assert child.getEffectiveLevel() == logging.DEBUG
    finally:
        io_logger.setLevel(old_level)


def test_spinner_disabled_writes_nothing(capsys):
    sp = Spinner(enabled=False)
    sp.set_message("x")
    sp.finish_with_message("done")
    assert capsys.readouterr().err == ""


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self) -> float:
        return self.t


def test_spinner_flushes_final_rate_limited_update(capsys):
    clock = _FakeClock()
    sp = Spinner(enabled=True, min_interval_s=0.1, clock=clock)
    clock.t += 1.0
    sp.set_message("first")
    clock.t += 0.01  # inside the rate-limit window: held as pending
    sp.set_message("last-frame")
    err_so_far = capsys.readouterr().err
    assert "first" in err_so_far
    assert "last-frame" not in err_so_far  # rate-limited, not yet drawn
    sp.finish_with_message("done")
    err = capsys.readouterr().err
    # The held update lands before the finish line replaces it.
    assert "last-frame" in err
    assert err.index("last-frame") < err.index("done")


def test_spinner_finish_silent_when_no_frame_drawn(capsys):
    clock = _FakeClock()
    sp = Spinner(enabled=True, min_interval_s=0.1, clock=clock)
    # No set_message ever drew a frame: finish has nothing to overwrite.
    sp.finish_with_message("done")
    assert capsys.readouterr().err == ""


def test_spinner_finish_after_frame_writes_once(capsys):
    clock = _FakeClock()
    sp = Spinner(enabled=True, min_interval_s=0.1, clock=clock)
    clock.t += 1.0
    sp.set_message("work")
    sp.finish_with_message("done")
    err = capsys.readouterr().err
    assert "done" in err and err.endswith("\n")
    # Second finish is a no-op: the frame was already consumed.
    sp.finish_with_message("again")
    assert capsys.readouterr().err == ""


def test_assign_partitions_round_robin():
    assert assign_partitions([3, 1, 2, 0, 5], 2) == [[0, 2, 5], [1, 3]]
    assert assign_partitions([0], 4) == [[0], [], [], []]


def test_scan_profile_counters():
    prof = ScanProfile()
    with prof.stage("x", items=10):
        pass
    with prof.stage("x", items=5):
        pass
    st = prof.stages["x"]
    assert st.items == 15
    assert st.items_per_sec > 0
    assert "x: " in prof.summary()


def test_stage_stats_rate_math():
    from kafka_topic_analyzer_tpu.utils.profiling import StageStats

    st = StageStats(seconds=2.0, items=100, bytes=4_000_000)
    assert st.items_per_sec == pytest.approx(50.0)
    assert st.mb_per_sec == pytest.approx(2.0)
    # Zero-duration stages report 0 rather than dividing by zero.
    empty = StageStats()
    assert empty.items_per_sec == 0.0
    assert empty.mb_per_sec == 0.0


def test_scan_profile_summary_order_and_mbs():
    prof = ScanProfile()
    # Insert out of pipeline order (a resumed scan snapshots first).
    for name in ("snapshot", "finalize", "dispatch", "zeta", "ingest"):
        with prof.stage(name, items=1, nbytes=1_000_000):
            pass
    names = [n for n, _ in prof.ordered_stages()]
    # Canonical pipeline order, then alphabetical for out-of-canon stages.
    assert names == ["ingest", "dispatch", "snapshot", "finalize", "zeta"]
    assert "MB" in prof.summary() and "MB/s" in prof.summary()


def test_scan_profile_stages_mirror_into_tracer():
    from kafka_topic_analyzer_tpu.obs.trace import SpanTracer

    tracer = SpanTracer()
    prof = ScanProfile(tracer=tracer)
    with prof.stage("ingest", items=3):
        pass
    (ev,) = tracer.events()
    assert ev["name"] == "ingest" and ev["cat"] == "stage"
    # Same measurement: the trace duration IS the profiled seconds.
    assert ev["dur"] == pytest.approx(prof.stages["ingest"].seconds * 1e6)


def test_maybe_jax_trace_noop_path():
    from kafka_topic_analyzer_tpu.utils.profiling import maybe_jax_trace

    # Falsy dirs skip the profiler entirely (no jax import needed).
    with maybe_jax_trace(None):
        pass
    with maybe_jax_trace(""):
        pass


def test_maybe_jax_trace_trace_path(monkeypatch, tmp_path):
    import contextlib

    import jax

    from kafka_topic_analyzer_tpu.utils.profiling import maybe_jax_trace

    seen = []

    @contextlib.contextmanager
    def fake_trace(profile_dir):
        seen.append(profile_dir)
        yield

    monkeypatch.setattr(jax.profiler, "trace", fake_trace)
    with maybe_jax_trace(str(tmp_path)):
        pass
    assert seen == [str(tmp_path)]


def test_timefmt_chrono_display():
    assert format_utc_seconds(0) == "1970-01-01 00:00:00 UTC"
    assert format_utc_seconds(1_600_000_000) == "2020-09-13 12:26:40 UTC"


def test_soak_pipeline(monkeypatch):
    """Bounded soak: a few million records through the full engine with
    prefetch, gated so default suite runs stay fast."""
    import os

    if not os.environ.get("KTA_STRESS"):
        pytest.skip("set KTA_STRESS=1 for the soak run")
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    spec = SyntheticSpec(
        num_partitions=8, messages_per_partition=500_000, keys_per_partition=50_000
    )
    cfg = AnalyzerConfig(
        num_partitions=8, batch_size=1 << 17, count_alive_keys=True,
        alive_bitmap_bits=24, enable_hll=True, enable_quantiles=True,
    )
    m = run_scan(
        "soak", SyntheticSource(spec), TpuBackend(cfg, init_now_s=0), 1 << 17
    ).metrics
    assert m.overall_count == 4_000_000


def test_soak_memory_is_o1(monkeypatch):
    """The analyzer's whole point at scale is O(1) state over an unbounded
    stream (SURVEY.md §5.7: the reference holds fixed-size counters,
    src/metric.rs:12-26; this build adds fixed-size sketches).  Drive many
    batches through the device backend and assert the client process RSS
    stays flat after warmup — a per-batch leak (device buffers, packed
    host buffers, jit cache growth) would compound over a 1B-message scan
    long before correctness tests noticed.  Gated: soak tier."""
    import os

    if not os.environ.get("KTA_STRESS"):
        pytest.skip("set KTA_STRESS=1 for the soak run")

    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    def rss_mb() -> float:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
        raise RuntimeError("no VmRSS")

    cfg = AnalyzerConfig(
        num_partitions=8, batch_size=1 << 17, count_alive_keys=True,
        alive_bitmap_bits=24, enable_hll=True, enable_quantiles=True,
    )
    spec = SyntheticSpec(
        num_partitions=8, messages_per_partition=1 << 16,
        keys_per_partition=50_000,
    )
    batches = [
        b.pad_to(cfg.batch_size)
        for b in SyntheticSource(spec).batches(cfg.batch_size)
    ]
    backend = TpuBackend(cfg, init_now_s=0)
    warmup_rounds, soak_rounds = 8, 64
    for _ in range(warmup_rounds):
        for b in batches:
            backend.update(b)
    backend.block_until_ready()
    base = rss_mb()
    for _ in range(soak_rounds):
        for b in batches:
            backend.update(b)
    backend.block_until_ready()
    grown = rss_mb() - base
    n = (warmup_rounds + soak_rounds) * sum(b.num_valid for b in batches)
    assert backend.finalize().overall_count == n
    # Allocator jitter allowance only: 64 rounds of a real per-batch leak
    # (one retained 2.3 MB packed buffer, say) would blow far past this.
    assert grown < 160, f"RSS grew {grown:.0f} MB over {soak_rounds} rounds"
