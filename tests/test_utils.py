"""Small utility coverage: env_logger-style level parsing, spinner, mesh
partition assignment, profiling counters."""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.parallel.mesh import assign_partitions
from kafka_topic_analyzer_tpu.utils.profiling import ScanProfile
from kafka_topic_analyzer_tpu.utils.progress import Spinner
from kafka_topic_analyzer_tpu.utils.timefmt import format_utc_seconds


def test_log_level_parsing():
    import logging

    from kafka_topic_analyzer_tpu.utils.log import parse_level

    assert parse_level("debug") == logging.DEBUG
    assert parse_level("warn") == logging.WARNING
    assert parse_level("module=debug,info") == logging.INFO  # first bare seg
    assert parse_level("nonsense") == logging.ERROR          # fallback
    assert parse_level("trace") == logging.DEBUG
    assert parse_level("off") == logging.CRITICAL


def test_spinner_disabled_writes_nothing(capsys):
    sp = Spinner(enabled=False)
    sp.set_message("x")
    sp.finish_with_message("done")
    assert capsys.readouterr().err == ""


def test_assign_partitions_round_robin():
    assert assign_partitions([3, 1, 2, 0, 5], 2) == [[0, 2, 5], [1, 3]]
    assert assign_partitions([0], 4) == [[0], [], [], []]


def test_scan_profile_counters():
    prof = ScanProfile()
    with prof.stage("x", items=10):
        pass
    with prof.stage("x", items=5):
        pass
    st = prof.stages["x"]
    assert st.items == 15
    assert st.items_per_sec > 0
    assert "x: " in prof.summary()


def test_timefmt_chrono_display():
    assert format_utc_seconds(0) == "1970-01-01 00:00:00 UTC"
    assert format_utc_seconds(1_600_000_000) == "2020-09-13 12:26:40 UTC"


def test_soak_pipeline(monkeypatch):
    """Bounded soak: a few million records through the full engine with
    prefetch, gated so default suite runs stay fast."""
    import os

    if not os.environ.get("KTA_STRESS"):
        pytest.skip("set KTA_STRESS=1 for the soak run")
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    spec = SyntheticSpec(
        num_partitions=8, messages_per_partition=500_000, keys_per_partition=50_000
    )
    cfg = AnalyzerConfig(
        num_partitions=8, batch_size=1 << 17, count_alive_keys=True,
        alive_bitmap_bits=24, enable_hll=True, enable_quantiles=True,
    )
    m = run_scan(
        "soak", SyntheticSource(spec), TpuBackend(cfg, init_now_s=0), 1 << 17
    ).metrics
    assert m.overall_count == 4_000_000


def test_soak_memory_is_o1(monkeypatch):
    """The analyzer's whole point at scale is O(1) state over an unbounded
    stream (SURVEY.md §5.7: the reference holds fixed-size counters,
    src/metric.rs:12-26; this build adds fixed-size sketches).  Drive many
    batches through the device backend and assert the client process RSS
    stays flat after warmup — a per-batch leak (device buffers, packed
    host buffers, jit cache growth) would compound over a 1B-message scan
    long before correctness tests noticed.  Gated: soak tier."""
    import os

    if not os.environ.get("KTA_STRESS"):
        pytest.skip("set KTA_STRESS=1 for the soak run")

    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    def rss_mb() -> float:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
        raise RuntimeError("no VmRSS")

    cfg = AnalyzerConfig(
        num_partitions=8, batch_size=1 << 17, count_alive_keys=True,
        alive_bitmap_bits=24, enable_hll=True, enable_quantiles=True,
    )
    spec = SyntheticSpec(
        num_partitions=8, messages_per_partition=1 << 16,
        keys_per_partition=50_000,
    )
    batches = [
        b.pad_to(cfg.batch_size)
        for b in SyntheticSource(spec).batches(cfg.batch_size)
    ]
    backend = TpuBackend(cfg, init_now_s=0)
    warmup_rounds, soak_rounds = 8, 64
    for _ in range(warmup_rounds):
        for b in batches:
            backend.update(b)
    backend.block_until_ready()
    base = rss_mb()
    for _ in range(soak_rounds):
        for b in batches:
            backend.update(b)
    backend.block_until_ready()
    grown = rss_mb() - base
    n = (warmup_rounds + soak_rounds) * sum(b.num_valid for b in batches)
    assert backend.finalize().overall_count == n
    # Allocator jitter allowance only: 64 rounds of a real per-batch leak
    # (one retained 2.3 MB packed buffer, say) would blow far past this.
    assert grown < 160, f"RSS grew {grown:.0f} MB over {soak_rounds} rounds"
