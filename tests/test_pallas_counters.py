"""Pallas MXU counter kernel vs the lax scatter-add path (interpret mode —
the same kernel runs compiled on real TPU)."""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.jax_support import jnp
from kafka_topic_analyzer_tpu.ops.counters import counters_update
from kafka_topic_analyzer_tpu.ops.pallas_counters import (
    BLOCK,
    pallas_counters_update,
)


def _random_arrays(b, p, seed, big_values=False):
    rng = np.random.default_rng(seed)
    return dict(
        partition=rng.integers(0, p, size=b).astype(np.int32),
        key_len=rng.integers(0, 60_000, size=b).astype(np.int32),
        value_len=rng.integers(
            0, (1 << 24) - 1 if big_values else 3000, size=b
        ).astype(np.int32),
        key_null=rng.random(b) < 0.1,
        value_null=rng.random(b) < 0.15,
        valid=rng.random(b) < 0.9,
    )


@pytest.mark.parametrize("p", [1, 3, 16, 64, 200, 300])
def test_pallas_matches_lax(p):
    b = 4 * BLOCK
    a = _random_arrays(b, p, seed=p)
    base = jnp.zeros((p, 7), dtype=jnp.int64)
    want = counters_update(
        base, a["partition"], a["key_len"], a["value_len"],
        jnp.asarray(a["key_null"]), jnp.asarray(a["value_null"]),
        jnp.asarray(a["valid"]), p,
    )
    got = pallas_counters_update(
        base, jnp.asarray(a["partition"]), jnp.asarray(a["key_len"]),
        jnp.asarray(a["value_len"]), jnp.asarray(a["key_null"]),
        jnp.asarray(a["value_null"]), jnp.asarray(a["valid"]), p,
        interpret=True,
    )
    assert np.array_equal(np.asarray(want), np.asarray(got))


def test_pallas_exact_at_16mb_values():
    """12-bit digit decomposition stays exact at the value-length cap."""
    b = BLOCK
    a = _random_arrays(b, 4, seed=9, big_values=True)
    base = jnp.zeros((4, 7), dtype=jnp.int64)
    want = counters_update(
        base, a["partition"], a["key_len"], a["value_len"],
        jnp.asarray(a["key_null"]), jnp.asarray(a["value_null"]),
        jnp.asarray(a["valid"]), 4,
    )
    got = pallas_counters_update(
        base, jnp.asarray(a["partition"]), jnp.asarray(a["key_len"]),
        jnp.asarray(a["value_len"]), jnp.asarray(a["key_null"]),
        jnp.asarray(a["value_null"]), jnp.asarray(a["valid"]), 4,
        interpret=True,
    )
    assert np.array_equal(np.asarray(want), np.asarray(got))


def test_pallas_backend_end_to_end_parity():
    """The flag through the full TpuBackend (interpret mode on CPU; the
    same kernel compiles on TPU)."""
    from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    spec = SyntheticSpec(
        num_partitions=5, messages_per_partition=3000, keys_per_partition=80
    )
    cfg = AnalyzerConfig(num_partitions=5, batch_size=2048, use_pallas_counters=True)
    a = run_scan("t", SyntheticSource(spec), CpuExactBackend(cfg, init_now_s=0), 2048).metrics
    b = run_scan("t", SyntheticSource(spec), TpuBackend(cfg, init_now_s=0), 2048).metrics
    assert np.array_equal(a.per_partition, b.per_partition)
    assert np.array_equal(a.per_partition_extremes, b.per_partition_extremes)


def test_pallas_under_sharded_mesh_matches_lax():
    """The kernel runs inside shard_map (check_vma relaxed): a sharded
    scan with the Pallas counter path reports the same metrics as the
    default lax scatter path on the same records."""
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.synthetic import (
        SyntheticSource,
        SyntheticSpec,
    )
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    spec = SyntheticSpec(
        num_partitions=5,
        messages_per_partition=3000,
        keys_per_partition=200,
        key_null_permille=50,
        tombstone_permille=100,
        seed=77,
    )

    def scan(use_pallas: bool):
        cfg = AnalyzerConfig(
            num_partitions=5,
            # Chunked input sharding: each space shard folds
            # batch_size / space_shards records, and the kernel needs
            # 1024-record chunks — so 2048 over a (4, 2) mesh.
            batch_size=2048,
            mesh_shape=(4, 2),
            use_pallas_counters=use_pallas,
        )
        backend = ShardedTpuBackend(cfg)
        return run_scan(
            "t", SyntheticSource(spec), backend, batch_size=2048
        ).metrics

    a, b = scan(False), scan(True)
    assert np.array_equal(a.per_partition, b.per_partition)
    assert a.overall_count == b.overall_count
    assert a.overall_size == b.overall_size


def test_bad_batch_size_rejected():
    a = _random_arrays(100, 2, seed=1)
    with pytest.raises(ValueError, match="multiple"):
        pallas_counters_update(
            jnp.zeros((2, 7), dtype=jnp.int64),
            jnp.asarray(a["partition"]), jnp.asarray(a["key_len"]),
            jnp.asarray(a["value_len"]), jnp.asarray(a["key_null"]),
            jnp.asarray(a["value_null"]), jnp.asarray(a["valid"]), 2,
            interpret=True,
        )
