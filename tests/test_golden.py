"""Golden-bytes conformance tier (VERDICT r2 next #4).

Every Kafka wire frame in this file is authored BYTE BY BYTE from the
public protocol specification (kafka.apache.org/protocol + KIP-98 record
batch layout, RFC 1952 gzip, the Snappy format description, the LZ4 frame
format spec, RFC 8878 zstd) using this file's OWN primitive writers —
``kafka_codec``'s encoders are never called to produce test inputs, so the
decode paths are checked against bytes that do not share authorship with
the codec under test.  Compressed variants use stdlib zlib/gzip (an
independent implementation) and hand-laid-out snappy/LZ4/zstd store-mode
streams.

Tiers:
1. Primitive cross-checks: in-file CRC32-C (Castagnoli) and xxHash32
   against published test vectors, then against the codec's CRC.
2. Decoder-level golden bodies: RecordBatch v2 (plain + each codec),
   Metadata v1/v12, ListOffsets v1/v7, Fetch v4/v12, ApiVersions v0/v3.
3. A golden BROKER: a socket server replaying only canned hand-authored
   responses (including the KIP-511 ApiVersions downgrade dance) drives
   the full client + CLI end to end.

Reference behaviors exercised: watermark-snapshot termination
(src/kafka.rs:60-72,119-121), per-message metric semantics
(src/metric.rs:207-252), alive-key tracking (src/metric.rs:288-305).
"""

import gzip
import socket
import struct
import threading

import pytest

from kafka_topic_analyzer_tpu.io import kafka_codec as kc

# ---------------------------------------------------------------------------
# Primitive writers (big-endian, per the Kafka protocol "Protocol Primitive
# Types" table).  Deliberately minimal and local to this file.


def i8(v):
    return struct.pack(">b", v)


def i16(v):
    return struct.pack(">h", v)


def i32(v):
    return struct.pack(">i", v)


def i64(v):
    return struct.pack(">q", v)


def u32(v):
    return struct.pack(">I", v)


def uvarint(v):
    """Unsigned LEB128 (Kafka UNSIGNED_VARINT)."""
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def zigzag(v):
    """Kafka VARINT/VARLONG: zigzag then LEB128."""
    return uvarint((v << 1) ^ (v >> 63))


def string(s):
    """Classic STRING: i16 length (-1 = null) + utf8."""
    if s is None:
        return i16(-1)
    b = s.encode()
    return i16(len(b)) + b


def compact_string(s):
    """Flexible COMPACT_STRING: uvarint(len+1), 0 = null."""
    if s is None:
        return uvarint(0)
    b = s.encode()
    return uvarint(len(b) + 1) + b


def carr(n):
    """COMPACT_ARRAY length prefix: uvarint(n+1)."""
    return uvarint(n + 1)


def tags():
    """Empty tagged-field section."""
    return uvarint(0)


# ---------------------------------------------------------------------------
# CRC32-C (Castagnoli): reflected polynomial 0x82F63B78, init/final
# xor 0xFFFFFFFF — written from the definition, table-driven.

_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data):
    c = 0xFFFFFFFF
    for b in bytes(data):
        c = (c >> 8) ^ _CRC32C_TABLE[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


# xxHash32 (for the LZ4 frame header checksum), from the published spec.

_XXP1, _XXP2, _XXP3, _XXP4, _XXP5 = (
    2654435761, 2246822519, 3266489917, 668265263, 374761393,
)
_M = 0xFFFFFFFF


def _rotl(x, r):
    return ((x << r) | (x >> (32 - r))) & _M


def xxh32(data, seed=0):
    data = bytes(data)
    n = len(data)
    if n >= 16:
        v1 = (seed + _XXP1 + _XXP2) & _M
        v2 = (seed + _XXP2) & _M
        v3 = seed
        v4 = (seed - _XXP1) & _M
        i = 0
        while i <= n - 16:
            for vi in range(4):
                (lane,) = struct.unpack_from("<I", data, i + 4 * vi)
                v = (v1, v2, v3, v4)[vi]
                v = (v + lane * _XXP2) & _M
                v = (_rotl(v, 13) * _XXP1) & _M
                if vi == 0:
                    v1 = v
                elif vi == 1:
                    v2 = v
                elif vi == 2:
                    v3 = v
                else:
                    v4 = v
            i += 16
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _M
    else:
        h = (seed + _XXP5) & _M
        i = 0
    h = (h + n) & _M
    while i <= n - 4:
        (lane,) = struct.unpack_from("<I", data, i)
        h = (h + lane * _XXP3) & _M
        h = (_rotl(h, 17) * _XXP4) & _M
        i += 4
    while i < n:
        h = (h + data[i] * _XXP5) & _M
        h = (_rotl(h, 11) * _XXP1) & _M
        i += 1
    h ^= h >> 15
    h = (h * _XXP2) & _M
    h ^= h >> 13
    h = (h * _XXP3) & _M
    h ^= h >> 16
    return h


# ---------------------------------------------------------------------------
# Independent store-mode compressors (each from its format spec).


def snappy_raw(data):
    """Snappy block format, literal elements only: uvarint uncompressed
    length preamble, then 00-tag literals of at most 60 bytes."""
    out = bytearray(uvarint(len(data)))
    for i in range(0, len(data), 60):
        chunk = data[i : i + 60]
        out.append((len(chunk) - 1) << 2)  # tag 00 = literal
        out += chunk
    return bytes(out)


def snappy_xerial(data):
    """xerial framing: magic, version 1, compat 1, then i32-length-prefixed
    raw-snappy blocks (the Kafka java client's snappy container)."""
    block = snappy_raw(data)
    return b"\x82SNAPPY\x00" + i32(1) + i32(1) + i32(len(block)) + block


def lz4_frame(data):
    """LZ4 Frame: magic, FLG (version 01, block-independent, no checksums,
    no content size), BD (64 KB max block), header checksum =
    (xxh32(FLG+BD) >> 8) & 0xFF, one compressed block holding a single
    literal-only sequence (spec: the last sequence is literals only), then
    the 0 EndMark."""
    flg, bd = 0x60, 0x40
    hc = (xxh32(bytes([flg, bd])) >> 8) & 0xFF
    n = len(data)
    token = min(n, 15) << 4
    ext = bytearray()
    if n >= 15:
        rem = n - 15
        while rem >= 255:
            ext.append(255)
            rem -= 255
        ext.append(rem)
    block = bytes([token]) + bytes(ext) + data
    assert len(block) < (1 << 31)
    return (
        struct.pack("<I", 0x184D2204)
        + bytes([flg, bd, hc])
        + struct.pack("<I", len(block))
        + block
        + struct.pack("<I", 0)  # EndMark
    )


def zstd_frame_raw(data):
    """RFC 8878 zstd frame: magic, single-segment frame header with a
    1-byte frame content size, one Raw (store) block marked last."""
    assert len(data) <= 255, "1-byte FCS golden frame"
    fhd = 0x20  # single_segment=1, FCS code 0 -> 1-byte FCS
    block_header = struct.pack("<I", (len(data) << 3) | (0 << 1) | 1)[:3]
    return (
        struct.pack("<I", 0xFD2FB528)
        + bytes([fhd, len(data)])
        + block_header
        + data
    )


# ---------------------------------------------------------------------------
# The golden topic: 3 records at offsets 0..2 (KIP-98 RecordBatch v2).

T0_MS = 1_600_000_000_000  # 2020-09-13T12:26:40Z
GOLDEN_RECORDS = [
    (0, T0_MS + 0, b"alpha", b"v-zero"),
    (1, T0_MS + 1, b"beta", None),  # tombstone
    (2, T0_MS + 2, None, b"anonymous"),  # unkeyed
]


def encode_record(offset_delta, ts_delta, key, value):
    body = bytearray()
    body += i8(0)  # record attributes
    body += zigzag(ts_delta)
    body += zigzag(offset_delta)
    if key is None:
        body += zigzag(-1)
    else:
        body += zigzag(len(key)) + key
    if value is None:
        body += zigzag(-1)
    else:
        body += zigzag(len(value)) + value
    body += zigzag(0)  # headers
    return zigzag(len(body)) + bytes(body)


def golden_records_section():
    out = bytearray()
    for off, ts, k, v in GOLDEN_RECORDS:
        out += encode_record(off, ts - T0_MS, k, v)
    return bytes(out)


def golden_batch(codec=0):
    """One RecordBatch v2 frame: 61-byte header + records section
    (compressed per ``codec``).  The CRC (CRC32-C) covers attributes
    through the end and EXCLUDES base_offset/batch_length/
    partition_leader_epoch/magic/crc."""
    section = golden_records_section()
    if codec == kc.COMPRESSION_GZIP:
        section = gzip.compress(section)
    elif codec == kc.COMPRESSION_SNAPPY:
        section = snappy_raw(section)
    elif codec == kc.COMPRESSION_LZ4:
        section = lz4_frame(section)
    elif codec == kc.COMPRESSION_ZSTD:
        section = zstd_frame_raw(section)
    crc_part = (
        i16(codec)          # attributes: low 3 bits = codec
        + i32(2)            # last_offset_delta
        + i64(T0_MS)        # first_timestamp
        + i64(T0_MS + 2)    # max_timestamp
        + i64(-1)           # producer_id
        + i16(-1)           # producer_epoch
        + i32(-1)           # base_sequence
        + i32(3)            # record count
        + section
    )
    after_length = i32(0) + i8(2) + u32(crc32c(crc_part)) + crc_part
    #              ^partition_leader_epoch  ^magic=2
    return i64(0) + i32(len(after_length)) + after_length


# ---------------------------------------------------------------------------
# Tier 1: primitive cross-checks.


def test_crc32c_known_vectors_and_codec_parity():
    # Published CRC-32C check value ("123456789" -> 0xE3069283).
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    # iSCSI CRC32C test vector: 32 bytes of zeros -> 0x8A9136AA (RFC 3720).
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    for payload in (b"", b"a", b"hello kafka", bytes(range(256)) * 3):
        assert kc._crc32c(payload) == crc32c(payload)


def test_xxh32_known_vectors():
    # Published xxHash32 sanity vectors.
    assert xxh32(b"") == 0x02CC5D05
    assert xxh32(b"Hello World") == 0xB1FD16EE


# ---------------------------------------------------------------------------
# Tier 2: decoder-level golden bodies.


def _expect_records(frame_iter):
    got = []
    for frame in frame_iter:
        for off, (ts_ms, key, value) in kc.decode_frame_records(frame):
            got.append((off, ts_ms, key, value))
    assert got == GOLDEN_RECORDS


def test_golden_record_batch_plain_python_decode():
    buf = golden_batch()
    _expect_records(kc.iter_batch_frames(buf, verify_crc=True))


def test_golden_record_batch_native_decode():
    from kafka_topic_analyzer_tpu.io.native import (
        decode_record_set_native,
        native_available,
        scan_record_set_native,
    )

    if not native_available():
        pytest.skip("native shim unavailable")
    buf = golden_batch()
    n, used, covered = scan_record_set_native(buf, verify_crc=True)
    assert (n, used, covered) == (3, len(buf), 3)
    soa, used, covered = decode_record_set_native(buf, verify_crc=True)
    assert used == len(buf) and covered == 3
    assert list(soa["offsets"]) == [0, 1, 2]
    assert list(soa["ts_ms"]) == [T0_MS, T0_MS + 1, T0_MS + 2]
    assert list(soa["key_len"]) == [5, 4, 0]
    assert list(soa["value_len"]) == [6, 0, 9]
    assert list(soa["key_null"]) == [0, 0, 1]
    assert list(soa["value_null"]) == [0, 1, 0]


@pytest.mark.parametrize(
    "codec",
    [
        kc.COMPRESSION_GZIP,
        kc.COMPRESSION_SNAPPY,
        kc.COMPRESSION_LZ4,
        kc.COMPRESSION_ZSTD,
    ],
)
def test_golden_record_batch_compressed(codec):
    buf = golden_batch(codec)
    _expect_records(kc.iter_batch_frames(buf, verify_crc=True))


def test_golden_snappy_xerial_framing():
    """The Kafka java client wraps snappy in xerial framing; decoders must
    accept both.  Exercised at the decompressor level (batch attributes
    carry only 'snappy', the framing is sniffed)."""
    from kafka_topic_analyzer_tpu.io.compression import snappy_decompress

    section = golden_records_section()
    assert snappy_decompress(snappy_xerial(section)) == section
    assert snappy_decompress(snappy_raw(section)) == section


GOLDEN_TOPIC = "golden.topic"


def metadata_v1_body(port, host="127.0.0.1"):
    return (
        i32(1)  # brokers
        + i32(1) + string(host) + i32(port) + string(None)  # rack null
        + i32(1)  # controller_id
        + i32(1)  # topics
        + i16(0) + string(GOLDEN_TOPIC) + i8(0)  # error, name, is_internal
        + i32(1)  # partitions
        + i16(0) + i32(0) + i32(1)  # error, partition 0, leader 1
        + i32(1) + i32(1)  # replicas [1]
        + i32(1) + i32(1)  # isr [1]
    )


def metadata_v12_body(port, host="127.0.0.1"):
    return (
        i32(0)  # throttle
        + carr(1)
        + i32(1) + compact_string(host) + i32(port)
        + compact_string(None) + tags()  # rack
        + compact_string(None)  # cluster_id
        + i32(1)  # controller_id
        + carr(1)
        + i16(0) + compact_string(GOLDEN_TOPIC)
        + b"\x00" * 16  # topic_id (v10+)
        + i8(0)  # is_internal
        + carr(1)
        + i16(0) + i32(0) + i32(1)  # error, partition, leader
        + i32(0)  # leader_epoch
        + carr(1) + i32(1)  # replicas
        + carr(1) + i32(1)  # isr
        + carr(0)  # offline_replicas
        + tags()
        + i32(-2147483648)  # topic_authorized_operations (v8+)
        + tags()
        + tags()
    )


def list_offsets_v1_body(offset):
    return (
        i32(1) + string(GOLDEN_TOPIC)
        + i32(1)
        + i32(0) + i16(0) + i64(-1) + i64(offset)  # pid, err, ts, offset
    )


def list_offsets_v7_body(offset):
    return (
        i32(0)  # throttle
        + carr(1) + compact_string(GOLDEN_TOPIC)
        + carr(1)
        + i32(0) + i16(0) + i64(-1) + i64(offset) + i32(0)  # +leader_epoch
        + tags() + tags() + tags()
    )


def fetch_v4_body(records):
    return (
        i32(0)  # throttle
        + i32(1) + string(GOLDEN_TOPIC)
        + i32(1)
        + i32(0) + i16(0)  # partition 0, error
        + i64(3)  # high watermark
        + i64(3)  # last_stable_offset
        + i32(0)  # aborted_transactions: empty
        + i32(len(records)) + records
    )


def fetch_v12_body(records):
    return (
        i32(0)  # throttle
        + i16(0)  # top-level error
        + i32(0)  # session_id
        + carr(1) + compact_string(GOLDEN_TOPIC)
        + carr(1)
        + i32(0) + i16(0)  # partition 0, error
        + i64(3) + i64(3) + i64(0)  # hw, last_stable, log_start
        + carr(0)  # aborted
        + i32(-1)  # preferred_read_replica
        + uvarint(len(records) + 1) + records  # COMPACT_BYTES
        + tags() + tags() + tags()
    )


APIS_V0 = [(kc.API_FETCH, 0, 4), (kc.API_LIST_OFFSETS, 0, 1),
           (kc.API_METADATA, 0, 1), (kc.API_VERSIONS, 0, 0)]


def api_versions_v0_body(error=0):
    out = i16(error) + i32(len(APIS_V0))
    for key, lo, hi in APIS_V0:
        out += i16(key) + i16(lo) + i16(hi)
    return out


def api_versions_v3_body():
    out = i16(0) + carr(len(APIS_V0))
    for key, lo, hi in APIS_V0:
        out += i16(key) + i16(lo) + i16(hi) + tags()
    return out + i32(0) + tags()


def test_golden_metadata_bodies_decode():
    for version, body in ((1, metadata_v1_body(9092)),
                          (12, metadata_v12_body(9092))):
        md = kc.decode_metadata_response(kc.ByteReader(body), version)
        assert md.brokers == {1: ("127.0.0.1", 9092)}
        assert md.controller_id == 1
        assert len(md.topics) == 1
        t = md.topics[0]
        assert (t.error, t.name) == (0, GOLDEN_TOPIC)
        assert [(p.error, p.partition, p.leader) for p in t.partitions] == [
            (0, 0, 1)
        ]


def test_golden_list_offsets_bodies_decode():
    assert kc.decode_list_offsets_response(
        kc.ByteReader(list_offsets_v1_body(3)), 1
    ) == {0: (0, 3, -1)}
    assert kc.decode_list_offsets_response(
        kc.ByteReader(list_offsets_v7_body(3)), 7
    ) == {0: (0, 3, 0)}


def test_golden_fetch_bodies_decode():
    records = golden_batch()
    for version, body in ((4, fetch_v4_body(records)),
                          (12, fetch_v12_body(records))):
        fps = kc.decode_fetch_response(kc.ByteReader(body), version)
        assert len(fps) == 1
        fp = fps[0]
        assert (fp.partition, fp.error, fp.high_watermark) == (0, 0, 3)
        assert bytes(fp.records) == records
        _expect_records(kc.iter_batch_frames(bytes(fp.records),
                                             verify_crc=True))


def test_golden_api_versions_bodies_decode():
    ranges = kc.decode_api_versions_response(
        kc.ByteReader(api_versions_v0_body()), 0
    )
    assert ranges[kc.API_FETCH] == (0, 4)
    assert ranges[kc.API_METADATA] == (0, 1)
    ranges3 = kc.decode_api_versions_response(
        kc.ByteReader(api_versions_v3_body()), 3
    )
    assert ranges3 == ranges
    with pytest.raises(kc.UnsupportedVersionError):
        kc.decode_api_versions_response(
            kc.ByteReader(api_versions_v0_body(error=35)), 3
        )


# ---------------------------------------------------------------------------
# Tier 3: the golden broker — canned hand-authored responses only.


class GoldenBroker:
    """Replays canned golden responses over real TCP.  Request handling
    reads only the universal header prefix (api_key, api_version,
    correlation_id — identical at every header version) and, for
    ListOffsets v1, the trailing (partition, timestamp) fields; request
    bodies are otherwise ignored.  Responses are the hand-authored bodies
    above behind a correlation-id echo — no kafka_codec encoder runs."""

    def __init__(self, codec=0):
        self.codec = codec
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn):
        try:
            while True:
                head = self._recv_exact(conn, 4)
                if head is None:
                    return
                (size,) = struct.unpack(">i", head)
                frame = self._recv_exact(conn, size)
                if frame is None:
                    return
                api_key, api_version, corr = struct.unpack(">hhi", frame[:8])
                body = self._respond(api_key, api_version, frame)
                conn.sendall(i32(len(body) + 4) + i32(corr) + body)
        except OSError:
            pass
        finally:
            conn.close()

    def _respond(self, api_key, api_version, frame):
        if api_key == kc.API_VERSIONS:
            if api_version >= 3:
                # KIP-511: a broker that does not speak v3 answers
                # UNSUPPORTED_VERSION in the v0 body format.
                return api_versions_v0_body(error=35)
            return api_versions_v0_body()
        if api_key == kc.API_METADATA:
            assert api_version == 1, f"unexpected Metadata v{api_version}"
            return metadata_v1_body(self.port)
        if api_key == kc.API_LIST_OFFSETS:
            assert api_version == 1
            (ts,) = struct.unpack(">q", frame[-8:])
            return list_offsets_v1_body(0 if ts == -2 else 3)
        if api_key == kc.API_FETCH:
            assert api_version == 4, f"unexpected Fetch v{api_version}"
            return fetch_v4_body(golden_batch(self.codec))
        raise AssertionError(f"golden broker got api_key {api_key}")

    @staticmethod
    def _recv_exact(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self.sock.close()


def _scan_golden_topic(capsys, codec=0, extra=()):
    from kafka_topic_analyzer_tpu.cli import main

    with GoldenBroker(codec) as broker:
        rc = main([
            "-t", GOLDEN_TOPIC,
            "-b", f"127.0.0.1:{broker.port}",
            "--librdkafka", "check.crcs=true",
            "-c", "--alive-bitmap-bits", "20",
            "--quiet",
        ] + list(extra))
    assert rc == 0
    return capsys.readouterr().out


def _assert_golden_report(out):
    # src/metric.rs semantics on the golden records: 3 total, 2 alive
    # (non-null values), 1 tombstone, 1 null key; sizes K=9 V=15;
    # averages divide by alive (=2); min/max message size exclude the
    # tombstone (r0=11, r2=9); alive keys: alpha in, beta tombstoned,
    # unkeyed ignored -> 1.
    assert f"Topic {GOLDEN_TOPIC}" in out
    assert "Topic Size: 24" in out
    assert "Largest Message: 11" in out
    assert "Smallest Message: 9" in out
    assert "Alive keys: 1" in out
    # 2020-09-13T12:26:40Z at second granularity, both ts in one second.
    assert "Earliest Message: 2020-09-13 12:26:40" in out
    assert "Latest Message: 2020-09-13 12:26:40" in out
    row = next(l for l in out.splitlines() if l.startswith("| 0 |"))
    cells = [c.strip() for c in row.strip("|").split("|")]
    # P, <OS, >OS, Total, Alive, Tmb, DR, K Null, K !Null, P-Bytes,
    # K-Bytes, V-Bytes, A K-Sz, A V-Sz, A M-Sz  (src/main.rs:150)
    assert cells == ["0", "0", "3", "3", "2", "1", "33.3333", "1", "2",
                     "24", "9", "15", "4", "7", "12"]


def test_golden_broker_end_to_end_cpu(capsys):
    _assert_golden_report(_scan_golden_topic(capsys, extra=["--backend", "cpu"]))


def test_golden_broker_end_to_end_tpu(capsys):
    _assert_golden_report(_scan_golden_topic(capsys, extra=["--backend", "tpu"]))


@pytest.mark.parametrize(
    "codec",
    [
        kc.COMPRESSION_GZIP,
        kc.COMPRESSION_SNAPPY,
        kc.COMPRESSION_LZ4,
        kc.COMPRESSION_ZSTD,
    ],
)
def test_golden_broker_compressed_end_to_end(capsys, codec):
    _assert_golden_report(_scan_golden_topic(capsys, codec))
