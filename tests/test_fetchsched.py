"""The process-wide fetch scheduler + verify-amortized cache (DESIGN.md
§25): ONE admission point for every remote byte — per-stream fairness,
demand-over-speculative reordering, bounded queue memory, clean shutdown
mid-fetch — and the trust latch that amortizes cache verification to one
sha256 per entry per process while keeping the PR-14 never-serve-poison
guarantee on first touch.  Remote scans must stay byte-identical to local
scans at ANY fetch concurrency, readahead depth, or cache state.
"""

import os
import threading
import time

import pytest
from fake_objstore import FakeObjectStore

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    SegmentFetchConfig,
    TransportRetryConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import fetchsched
from kafka_topic_analyzer_tpu.io.fetchsched import (
    FetchScheduler,
    default_concurrency,
)
from kafka_topic_analyzer_tpu.io.segfile import (
    SegmentFileSource,
    write_segment_from_batches,
)
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.obs.registry import default_registry

pytestmark = pytest.mark.fetchsched

SPEC = SyntheticSpec(
    num_partitions=3,
    messages_per_partition=2_000,
    keys_per_partition=90,
    tombstone_permille=130,
    seed=11,
)
FAST_RETRY = TransportRetryConfig(
    backoff_ms=1, backoff_max_ms=4, retry_budget=4
)


def fetch_cfg(readahead=2, cache=None, fc="auto"):
    return SegmentFetchConfig(
        readahead=readahead, cache_dir=cache, retry=FAST_RETRY,
        timeout_s=5.0, fetch_concurrency=fc,
    )


@pytest.fixture(autouse=True)
def _fresh_scheduler():
    """Every test starts (and leaves) the process with NO singleton and
    no remembered --fetch-concurrency: the latch/pool state under test is
    deliberately process-global."""
    fetchsched._reset_for_tests()
    yield
    fetchsched._reset_for_tests()


@pytest.fixture()
def seg_dir(tmp_path):
    src = SyntheticSource(SPEC)
    d = tmp_path / "segs"
    d.mkdir()
    for p in src.partitions():
        write_segment_from_batches(
            str(d), "t", p, list(src.batches(700, partitions=[p]))
        )
    return str(d)


def cpu_cfg(**kw):
    base = dict(
        num_partitions=3, batch_size=700, count_alive_keys=True,
        alive_bitmap_bits=18, enable_hll=True, hll_p=8,
    )
    base.update(kw)
    return AnalyzerConfig(**base)


def scan_doc(result):
    d = result.metrics.to_dict(result.start_offsets, result.end_offsets)
    d["degraded"] = dict(result.degraded_partitions)
    return d


def metric_total(name, **labels):
    m = default_registry().snapshot().get(name)
    if not m:
        return 0.0
    return sum(
        s["value"] for s in m["samples"]
        if all(s["labels"].get(k) == v for k, v in labels.items())
    )


class _Gate:
    """A fetch that parks its worker until released — the deterministic
    way to build up a queue behind a busy pool."""

    def __init__(self, tag="gate", order=None):
        self.tag = tag
        self.order = order
        self.started = threading.Event()
        self.release = threading.Event()

    def __call__(self):
        self.started.set()
        assert self.release.wait(10), "gate never released"
        if self.order is not None:
            self.order.append(self.tag)
        return self.tag


def _recorder(tag, order, lock):
    def fn():
        with lock:
            order.append(tag)
        return tag
    return fn


# ---------------------------------------------------------------------------
# scheduler units


def test_configuration_explicit_beats_auto_hints():
    assert SegmentFetchConfig.parse(
        fetch_concurrency="8"
    ).resolve_concurrency() == 8
    assert SegmentFetchConfig.parse(
        fetch_concurrency="auto"
    ).resolve_concurrency() is None
    with pytest.raises(ValueError, match="fetch.concurrency|fetch-concurrency"):
        SegmentFetchConfig.parse(fetch_concurrency="0")
    with pytest.raises(ValueError, match="fetch-concurrency"):
        SegmentFetchConfig.parse(fetch_concurrency="many")
    # Explicit flag sizes the singleton; later auto hints never override.
    fetchsched.configure(3, explicit=True)
    fetchsched.note_streams(64)
    assert fetchsched.get_scheduler().concurrency == 3
    fetchsched._reset_for_tests()
    # Under auto, stream hints grow the pool (capped), never shrink it.
    fetchsched.note_streams(1)
    base = fetchsched.get_scheduler().concurrency
    assert base == default_concurrency()
    fetchsched.note_streams(64)
    assert fetchsched.get_scheduler().concurrency == 16


def test_fairness_deep_backlog_cannot_starve_a_sibling_stream():
    """Round-robin across streams: stream B's FIRST request is served
    after at most one of stream A's, no matter how deep A's speculative
    backlog is (two fleet topics share one pool without stalls)."""
    sched = FetchScheduler(1)
    order, lock = [], threading.Lock()
    try:
        g, a, b = sched.stream(), sched.stream(), sched.stream()
        gate = _Gate(order=order)
        g.submit(gate, speculative=False)
        assert gate.started.wait(5)
        tickets = [
            a.submit(_recorder(f"a{i}", order, lock), seq=i)
            for i in range(5)
        ]
        tickets.append(b.submit(_recorder("b0", order, lock), seq=0))
        gate.release.set()
        for t in tickets:
            assert t.wait(10)
        assert order[0] == "gate"
        assert order.index("b0") <= 2, order
        # Within stream A, chunks still ran in plan order.
        a_done = [x for x in order if x.startswith("a")]
        assert a_done == sorted(a_done)
    finally:
        sched.shutdown()


def test_weighted_fairness_share_matches_weights():
    """Smooth weighted round-robin: a stream carrying 3× the partitions
    gets 3× the picks — deterministically interleaved (never 3 in a
    burst then 1), so the light stream's latency stays bounded."""
    sched = FetchScheduler(1)
    order, lock = [], threading.Lock()
    try:
        g = sched.stream()
        a = sched.stream(weight=3.0)
        b = sched.stream(weight=1.0)
        gate = _Gate(order=order)
        g.submit(gate, speculative=False)
        assert gate.started.wait(5)
        tickets = [
            a.submit(_recorder(f"a{i}", order, lock), seq=i)
            for i in range(6)
        ] + [
            b.submit(_recorder(f"b{i}", order, lock), seq=i)
            for i in range(2)
        ]
        gate.release.set()
        for t in tickets:
            assert t.wait(10)
        served = order[1:]  # drop the gate
        # First full weight cycle (4 picks): 3 of A, 1 of B — and the
        # smooth property: B is served INSIDE the cycle, not appended.
        assert sum(1 for x in served[:4] if x.startswith("a")) == 3
        assert sum(1 for x in served[:4] if x.startswith("b")) == 1
        # Whole run honours the 3:1 share and per-stream plan order.
        for s in ("a", "b"):
            got = [x for x in served if x.startswith(s)]
            assert got == sorted(got)
    finally:
        sched.shutdown()


def test_set_weight_rebalances_a_live_stream():
    """set_weight() takes effect on the next pick: a stream that starts
    equal and then declares a heavier plan immediately earns the larger
    share (segfile registers its plan size on first schedule())."""
    sched = FetchScheduler(1)
    order, lock = [], threading.Lock()
    try:
        g, a, b = sched.stream(), sched.stream(), sched.stream()
        a.set_weight(5.0)
        gate = _Gate(order=order)
        g.submit(gate, speculative=False)
        assert gate.started.wait(5)
        tickets = [
            a.submit(_recorder(f"a{i}", order, lock), seq=i)
            for i in range(5)
        ] + [
            b.submit(_recorder(f"b{i}", order, lock), seq=i)
            for i in range(2)
        ]
        gate.release.set()
        for t in tickets:
            assert t.wait(10)
        served = order[1:]
        # One full cycle of 6 picks carries 5 of A and 1 of B.
        assert sum(1 for x in served[:6] if x.startswith("a")) == 5
        with pytest.raises(ValueError):
            a.set_weight(0.0)
    finally:
        sched.shutdown()


def test_equal_weights_are_exact_round_robin():
    """The SWRR degenerate case: every weight 1.0 alternates strictly in
    registration order — the pre-weight fairness contract, unchanged."""
    sched = FetchScheduler(1)
    order, lock = [], threading.Lock()
    try:
        g, a, b = sched.stream(), sched.stream(), sched.stream()
        gate = _Gate(order=order)
        g.submit(gate, speculative=False)
        assert gate.started.wait(5)
        tickets = [
            a.submit(_recorder(f"a{i}", order, lock), seq=i)
            for i in range(3)
        ] + [
            b.submit(_recorder(f"b{i}", order, lock), seq=i)
            for i in range(3)
        ]
        gate.release.set()
        for t in tickets:
            assert t.wait(10)
        assert order[1:] == ["a0", "b0", "a1", "b1", "a2", "b2"]
    finally:
        sched.shutdown()


def test_weighted_remote_scan_stays_byte_identical(seg_dir):
    """Weights change WHO is picked next, never WHAT is read: a remote
    scan through auto-weighted streams (segfile registers plan sizes)
    matches the local referee byte for byte."""
    local = scan_doc(
        run_scan(
            "t", SegmentFileSource(seg_dir, "t"),
            CpuExactBackend(cpu_cfg(), init_now_s=10**10), 700,
        )
    )
    with FakeObjectStore(seg_dir) as store:
        remote = scan_doc(
            run_scan(
                "t",
                SegmentFileSource(
                    store.url, "t", fetch=fetch_cfg(readahead=3, fc=2),
                ),
                CpuExactBackend(cpu_cfg(), init_now_s=10**10), 700,
            )
        )
    assert remote == local


def test_deadline_promotion_jumps_demand_past_speculation():
    """The deadline rule: promoting a queued speculative request to
    DEMAND books {deadline-promotion}, and serving it ahead of
    earlier-submitted speculation books {demand-over-speculative}."""
    promo0 = metric_total(
        "kta_fetch_sched_reorders_total", reason="deadline-promotion"
    )
    jump0 = metric_total(
        "kta_fetch_sched_reorders_total", reason="demand-over-speculative"
    )
    sched = FetchScheduler(1)
    order, lock = [], threading.Lock()
    try:
        g, a = sched.stream(), sched.stream()
        gate = _Gate(order=order)
        g.submit(gate, speculative=False)
        assert gate.started.wait(5)
        tickets = [
            a.submit(_recorder(f"s{i}", order, lock), seq=i)
            for i in range(3)
        ]
        # The consumer reached chunk 2 while its request was still
        # queued read-ahead: promote it past s0/s1.
        assert sched.promote(tickets[2])
        gate.release.set()
        for t in tickets:
            assert t.wait(10)
        assert order == ["gate", "s2", "s0", "s1"]
        assert metric_total(
            "kta_fetch_sched_reorders_total", reason="deadline-promotion"
        ) - promo0 == 1
        assert metric_total(
            "kta_fetch_sched_reorders_total", reason="demand-over-speculative"
        ) - jump0 >= 1
        # Promotion is a QUEUED-only transition: done tickets refuse.
        assert not sched.promote(tickets[0])
    finally:
        sched.shutdown()


def test_occupancy_gauges_track_queue_and_inflight_then_settle():
    q0 = metric_total("kta_fetch_sched_queue_depth")
    f0 = metric_total("kta_fetch_sched_inflight")
    wait0 = metric_total("kta_fetch_sched_wait_seconds_total")
    sched = FetchScheduler(2)
    try:
        s = sched.stream()
        gates = [_Gate(f"g{i}") for i in range(2)]
        gate_tickets = [s.submit(g, speculative=False) for g in gates]
        for g in gates:
            assert g.started.wait(5)
        queued = [s.submit(lambda: None, seq=i) for i in range(8)]
        assert metric_total("kta_fetch_sched_queue_depth") - q0 == 8
        assert metric_total("kta_fetch_sched_inflight") - f0 == 2
        for g in gates:
            g.release.set()
        for t in gate_tickets + queued:
            assert t.wait(10)
        assert metric_total("kta_fetch_sched_queue_depth") - q0 == 0
        assert metric_total("kta_fetch_sched_inflight") - f0 == 0
        # The queued requests sat behind the gates: wait time was booked.
        assert metric_total("kta_fetch_sched_wait_seconds_total") > wait0
    finally:
        sched.shutdown()


def test_clean_shutdown_mid_fetch_cancels_queued_drains_inflight():
    c0 = metric_total("kta_fetch_sched_cancelled_total")
    q0 = metric_total("kta_fetch_sched_queue_depth")
    sched = FetchScheduler(1)
    s = sched.stream()
    gate = _Gate()
    gate_ticket = s.submit(gate, speculative=False)
    assert gate.started.wait(5)
    queued = [s.submit(lambda: None, seq=i) for i in range(3)]
    joiner = threading.Thread(target=sched.shutdown, kwargs={"wait": True})
    joiner.start()
    # Queued requests are cancelled immediately (booked), even while the
    # in-flight fetch is still on its worker...
    for t in queued:
        assert t.wait(10) and t.cancelled
    assert metric_total("kta_fetch_sched_cancelled_total") - c0 == 3
    assert gate_ticket.state != 3  # the in-flight fetch was NOT cancelled
    # ...and the in-flight fetch completes cleanly, then workers exit.
    gate.release.set()
    joiner.join(timeout=10)
    assert not joiner.is_alive()
    assert gate_ticket.result() == "gate"
    assert metric_total("kta_fetch_sched_queue_depth") - q0 == 0
    with pytest.raises(RuntimeError, match="shut down"):
        sched.stream()


def test_errors_are_redelivered_to_the_waiter_run_all_is_atomic():
    sched = FetchScheduler(2)
    try:
        def boom():
            raise OSError("wire fell over")

        with pytest.raises(OSError, match="wire fell over"):
            sched.run(boom)
        assert sched.run(lambda: 41) == 41
        # run_all: results in submission order; the FIRST failure by
        # order is re-raised only after every request settled.
        settled = threading.Event()

        def late_ok():
            assert settled.wait(10)
            return "late"

        def fail_then_release():
            settled.set()
            raise ValueError("first by order")

        with pytest.raises(ValueError, match="first by order"):
            sched.run_all([fail_then_release, late_ok])
        assert sched.run_all([lambda: 1, lambda: 2, lambda: 3]) == [1, 2, 3]
    finally:
        sched.shutdown()


def test_release_cancels_a_scheduled_fetch_that_never_started(seg_dir):
    """Satellite: RemoteSegmentFile.release() cancels its not-yet-started
    scheduler request (booked) — degraded-skip/teardown paths must not
    pay for bytes nobody will read."""
    from kafka_topic_analyzer_tpu.io.segfile import (
        HEADER_SIZE,
        RemoteSegmentFile,
    )

    chunk = sorted(
        f for f in os.listdir(seg_dir) if f.endswith(".ktaseg")
    )[0]
    path = os.path.join(seg_dir, chunk)
    raw = open(path, "rb").read()
    seg = RemoteSegmentFile(
        lambda validate: raw, chunk, "mem://", len(raw), raw[:HEADER_SIZE]
    )
    c0 = metric_total("kta_fetch_sched_cancelled_total")
    sched = FetchScheduler(1)
    try:
        s = sched.stream()
        gate = _Gate()
        s.submit(gate, speculative=False)
        assert gate.started.wait(5)
        seg._pending = s.submit(seg.ensure_body, seq=7)
        pending = seg._pending
        seg.release()
        assert pending.cancelled
        assert seg._pending is None
        assert metric_total("kta_fetch_sched_cancelled_total") - c0 == 1
        gate.release.set()
    finally:
        sched.shutdown()
    # A later touch still fetches fine — cancellation dropped read-ahead,
    # not the chunk.
    assert seg.ensure_body().nbytes == len(raw)


# ---------------------------------------------------------------------------
# remote-vs-local byte-identity across the concurrency surface


def test_remote_byte_identity_workers_x_superbatch_x_readahead(seg_dir):
    """The round-14 matrix re-run through the ONE shared scheduler, at a
    deliberately tiny pool (--fetch-concurrency 2) so demand and
    speculation genuinely queue: workers × K × readahead must stay
    byte-identical to the local referee."""
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import DispatchConfig

    cfg = cpu_cfg(batch_size=256, enable_quantiles=True)
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        TpuBackend(cfg, init_now_s=10**10), 256,
    ))
    with FakeObjectStore(seg_dir) as store:
        for workers in (1, 4):
            for k in (1, 4):
                for readahead in (0, 2):
                    backend = TpuBackend(
                        cfg, init_now_s=10**10,
                        dispatch=DispatchConfig(superbatch=k),
                    )
                    got = run_scan(
                        "t",
                        SegmentFileSource(
                            store.url, "t",
                            fetch=fetch_cfg(readahead, fc=2),
                        ),
                        backend, 256, ingest_workers=workers,
                    )
                    assert got.superbatch_k == k
                    assert scan_doc(got) == ref, (workers, k, readahead)
        assert fetchsched.get_scheduler().concurrency == 2
    # Everything drained and settled: every occupancy gauge back at zero.
    assert metric_total("kta_segstore_readahead_occupancy") == 0
    assert metric_total("kta_fetch_sched_queue_depth") == 0
    assert metric_total("kta_fetch_sched_inflight") == 0


def test_remote_byte_identity_across_fetch_concurrency(seg_dir):
    cfg = cpu_cfg()
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    with FakeObjectStore(seg_dir) as store:
        for fc in (1, "auto"):
            fetchsched._reset_for_tests()
            got = run_scan(
                "t",
                SegmentFileSource(
                    store.url, "t", fetch=fetch_cfg(2, fc=fc)
                ),
                CpuExactBackend(cfg, init_now_s=10**10), 700,
                ingest_workers=4,
            )
            assert scan_doc(got) == ref, fc


def test_readahead_window_bounds_outstanding_chunks(seg_dir):
    """Memory bound: the shared pool never holds more than
    streams × (readahead + 1) fetched-but-unconsumed chunks — sampled
    through the occupancy gauge across a latency-injected scan."""
    cfg = cpu_cfg()
    peak, stop = [0.0], threading.Event()

    def sampler():
        while not stop.is_set():
            peak[0] = max(
                peak[0], metric_total("kta_segstore_readahead_occupancy")
            )
            time.sleep(0.0005)

    th = threading.Thread(target=sampler)
    th.start()
    try:
        with FakeObjectStore(seg_dir, latency_ms=5) as store:
            got = run_scan(
                "t",
                SegmentFileSource(store.url, "t", fetch=fetch_cfg(2)),
                CpuExactBackend(cfg, init_now_s=10**10), 700,
                ingest_workers=2,
            )
    finally:
        stop.set()
        th.join()
    assert got.ingest_workers == 2
    assert 0 < peak[0] <= 2 * (2 + 1)
    assert metric_total("kta_segstore_readahead_occupancy") == 0


# ---------------------------------------------------------------------------
# the verify-amortized cache (trust latch)


def test_latched_hit_skips_hashing_first_touch_still_verifies(
    seg_dir, tmp_path
):
    cfg = cpu_cfg()
    cache = str(tmp_path / "cache")
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    with FakeObjectStore(seg_dir) as store:
        fetch = fetch_cfg(2, cache=cache)
        # Cold: fills the cache (put does NOT latch — trust is only ever
        # granted by a verifying read).
        run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        latched0 = metric_total("kta_segstore_cache_verify_latched_total")
        # Warm #1: every hit re-hashes (first touch this process) and
        # latches.
        verify0 = metric_total("kta_segstore_cache_verify_seconds_total")
        got = run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        assert scan_doc(got) == ref
        assert metric_total(
            "kta_segstore_cache_verify_seconds_total"
        ) > verify0
        assert metric_total(
            "kta_segstore_cache_verify_latched_total"
        ) == latched0
        # Warm #2: all three hits ride the latch — ZERO hashing seconds
        # booked, the latched-hit counter advances instead.
        verify1 = metric_total("kta_segstore_cache_verify_seconds_total")
        before = sum(store.body_gets.values())
        got = run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        assert scan_doc(got) == ref
        assert sum(store.body_gets.values()) == before
        assert metric_total(
            "kta_segstore_cache_verify_seconds_total"
        ) == verify1
        assert metric_total(
            "kta_segstore_cache_verify_latched_total"
        ) - latched0 == 3


def test_first_touch_poison_still_evicted_and_booked(seg_dir, tmp_path):
    """The PR-14 guarantee survives amortization: bytes that rotted in
    the cache BEFORE this process ever verified them are caught on first
    touch — evicted, booked, re-fetched — and the trust latch never
    served them."""
    cfg = cpu_cfg()
    cache = str(tmp_path / "cache")
    ref = scan_doc(run_scan(
        "t", SegmentFileSource(seg_dir, "t"),
        CpuExactBackend(cfg, init_now_s=10**10), 700,
    ))
    with FakeObjectStore(seg_dir) as store:
        fetch = fetch_cfg(2, cache=cache)
        run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        entry = sorted(
            f for f in os.listdir(cache) if f.endswith(".seg")
        )[0]
        path = os.path.join(cache, entry)
        data = bytearray(open(path, "rb").read())
        data[4321] ^= 0x10
        open(path, "wb").write(bytes(data))
        latched0 = metric_total("kta_segstore_cache_verify_latched_total")
        poisoned0 = metric_total(
            "kta_segstore_fallback_total", reason="cache-poisoned"
        )
        before = sum(store.body_gets.values())
        got = run_scan(
            "t", SegmentFileSource(store.url, "t", fetch=fetch),
            CpuExactBackend(cfg, init_now_s=10**10), 700,
        )
        assert scan_doc(got) == ref
        assert sum(store.body_gets.values()) - before == 1
        assert metric_total(
            "kta_segstore_fallback_total", reason="cache-poisoned"
        ) - poisoned0 == 1
        assert metric_total(
            "kta_segstore_cache_verify_latched_total"
        ) == latched0


def test_trust_latch_drops_on_eviction_and_repopulation(tmp_path):
    from kafka_topic_analyzer_tpu.io.objstore import SegmentCache

    cache = SegmentCache(str(tmp_path / "c"), 1 << 20, "store")
    latched0 = metric_total("kta_segstore_cache_verify_latched_total")
    cache.put("a", 3, b"abc")
    assert bytes(cache.get("a", 3)) == b"abc"  # verifying read: latches
    assert bytes(cache.get("a", 3)) == b"abc"  # latched hit
    assert metric_total(
        "kta_segstore_cache_verify_latched_total"
    ) - latched0 == 1
    # Eviction unlatches: the digest's next appearance re-verifies.
    cache.evict("a", 3)
    cache.put("a", 3, b"abc")
    assert bytes(cache.get("a", 3)) == b"abc"
    assert metric_total(
        "kta_segstore_cache_verify_latched_total"
    ) - latched0 == 1
    # Re-population (overwrite) also unlatches.
    cache.put("a", 3, b"abc")
    assert bytes(cache.get("a", 3)) == b"abc"
    assert metric_total(
        "kta_segstore_cache_verify_latched_total"
    ) - latched0 == 1
    # And a further read of the re-verified entry rides the latch again.
    assert bytes(cache.get("a", 3)) == b"abc"
    assert metric_total(
        "kta_segstore_cache_verify_latched_total"
    ) - latched0 == 2


# ---------------------------------------------------------------------------
# one pool across a fleet


def test_two_topic_fleet_shares_one_pool_without_cross_topic_stalls(
    tmp_path,
):
    from kafka_topic_analyzer_tpu.fleet.scheduler import (
        FleetScheduler,
        TopicSeed,
    )
    from kafka_topic_analyzer_tpu.fleet.service import FleetService

    d = tmp_path / "segs"
    d.mkdir()
    specs = {
        "t": SPEC,
        "u": SyntheticSpec(
            num_partitions=3, messages_per_partition=1_500,
            keys_per_partition=70, tombstone_permille=90, seed=23,
        ),
    }
    refs = {}
    for topic, spec in specs.items():
        src = SyntheticSource(spec)
        for p in src.partitions():
            write_segment_from_batches(
                str(d), topic, p, list(src.batches(700, partitions=[p]))
            )
        refs[topic] = scan_doc(run_scan(
            topic, SegmentFileSource(str(d), topic),
            CpuExactBackend(cpu_cfg(), init_now_s=10**10), 700,
        ))
    with FakeObjectStore(str(d), latency_ms=2) as store:
        svc = FleetService(
            [TopicSeed(name=t, partitions=3) for t in specs],
            lambda t: SegmentFileSource(
                store.url, t, fetch=fetch_cfg(2, fc=4)
            ),
            lambda t, parts, grant: CpuExactBackend(
                cpu_cfg(num_partitions=parts), init_now_s=10**10
            ),
            700,
            FleetScheduler(4, 4, 2),
        )
        fr = svc.run_batch()
    assert {t: fr.statuses[t].status for t in specs} == {
        "t": "ok", "u": "ok"
    }
    for topic in specs:
        assert scan_doc(fr.results[topic]) == refs[topic], topic
    # ONE pool served both topics, sized by the explicit flag — and it
    # drained clean.
    assert fetchsched.get_scheduler().concurrency == 4
    assert metric_total("kta_fetch_sched_queue_depth") == 0
    assert metric_total("kta_fetch_sched_inflight") == 0
    assert metric_total("kta_segstore_readahead_occupancy") == 0
